"""Fleet bench: multi-replica serving under chaos + disaggregated roles.

Drives the :class:`~deepspeed_tpu.serving.FleetEngine` (serving/fleet.py)
through the two fleet scenarios the ROADMAP names:

- **chaos failover** — a 3-replica fleet on the injectable fake clock
  serves deterministic traffic while one replica is killed mid-stream
  (the seeded ``FleetChaosConfig`` fault). The oracle: ZERO requests
  lost — every rid retires with a terminal status, the killed replica's
  queued and in-flight requests requeue onto survivors (typed
  ``REQUEUED``, ``attempts`` bumped) and still produce bit-identical
  output to solo ``generate()`` (per-request RNG folds from the seed);
  survivors' compile counters stay FROZEN through the whole event (a
  failover must never compile-storm).
- **disaggregated prefill/decode** — dedicated prefill replicas run
  chunked prefill to completion, hand finished KV to decode replicas as
  a host-mediated page transfer (``export_slot``/``import_slot`` on the
  PR-7 pool), and the disaggregated output is bit-identical to a single
  engine's on the same seeds.

``--smoke`` is the CPU tier-1 gate (wired via tests/unit/test_fleet.py,
same pattern as bench_serving.py): asserts both oracles plus a warm
``add_replica`` join compiling NOTHING, and writes ``FLEET_BENCH.json``.
The disaggregated phase additionally runs with distributed tracing ON
and a decode-replica kill mid-traffic: it asserts the hop sum-to-e2e
invariant (every completed request's queue_wait/prefill/handoff_wait/
import/decode hops tile its e2e wall within 1% on the fake clock), a
route-audit entry for every routing decision, and a merged fleet
Chrome trace (replicas as pids, cross-replica request flows) that
passes ``validate_chrome_trace`` — written to ``FLEET_TRACE.json``.
Prints one JSON line ending in "smoke-pass"; exits nonzero on failure.
"""

import dataclasses
import json
import os
import sys

import numpy as np


class TickClock:
    """Deterministic injectable clock (+dt per read): the whole fleet —
    schedulers, watchdogs, goodput ledgers, deadline sweeps — runs on
    fake time, so the bench is bit-reproducible on any machine."""

    def __init__(self, dt=0.001):
        self.t = 0.0
        self.dt = dt

    def __call__(self):
        self.t += self.dt
        return self.t

    def advance(self, s):
        self.t += s


def build_engine(max_len=48):
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, tiny_test

    cfg = tiny_test(n_layer=2, d_model=64, d_ff=128, n_head=4,
                    max_seq=max_len, dtype=jnp.float32)
    model = build_model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return ds.init_inference(model, params,
                             {"dtype": "float32", "eos_token_id": 510})


def build_fleet(eng, replicas=3, prefill_replicas=0, slots=2, max_len=48,
                chunk=16, clock=None, chaos=None, **serving_extra):
    from deepspeed_tpu.serving import FleetEngine

    return FleetEngine(eng, {"slots": slots, "max_len": max_len,
                             "prefill_chunk": chunk, "temperature": 0.8,
                             "top_k": 20, "goodput": True,
                             **serving_extra},
                       replicas=replicas,
                       prefill_replicas=prefill_replicas,
                       clock=clock, chaos=chaos)


def solo_oracle(eng, prompt, max_new, seed, max_len):
    """The documented parity oracle: single-request generate() with the
    request's seed and the serving cache length."""
    import jax.numpy as jnp

    return np.asarray(eng.generate(
        jnp.asarray(np.asarray(prompt)[None], jnp.int32), max_new,
        temperature=0.8, top_k=20, request_seeds=[seed],
        cache_len=max_len))[0]


def traffic(n, seed, lengths=(5, 16, 20, 30)):
    """Deterministic prompt stream over a FIXED length set — it spans
    every chunk-bucket shape (pad, exact, overlap, multi-chunk) so
    warmup covers what the main phase uses (the compile freeze is only
    meaningful if shapes repeat), and stays SMALL because every unique
    length also costs one solo-oracle generate() compile."""
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, 256, (lengths[i % len(lengths)],))
             .astype(np.int32), 6, 100 + i) for i in range(n)]


def drive(fleet, reqs, kill_after=None, max_iterations=100_000):
    """Submit ``reqs`` (prompt, max_new, seed), run to completion,
    return {rid: Request}. ``kill_after`` arms the fleet chaos monkey
    ``kill_after`` fleet iterations from NOW (mid-traffic, independent
    of how many warmup iterations ran before)."""
    rids = [fleet.submit(p, mn, seed=sd) for p, mn, sd in reqs]
    if kill_after is not None:
        fleet.chaos.cfg = dataclasses.replace(
            fleet.chaos.cfg,
            kill_replica_step=fleet.chaos._iterations + kill_after)
    done = {}
    it = 0
    while len(done) < len(rids):
        for req in fleet.step():
            done[req.rid] = req
            fleet.results.pop(req.rid, None)
        it += 1
        if it > max_iterations:
            raise RuntimeError("fleet bench driver wedged")
    return rids, done


# ------------------------------------------------------------------ smoke
def smoke():
    """CPU tier-1 gate: chaos-kill zero-loss + frozen compiles + warm
    join + disaggregated parity. Everything on the fake clock."""
    from deepspeed_tpu.serving import RequestStatus

    max_len = 48
    res = {"smoke": True}
    eng = build_engine(max_len)     # ONE engine (and one solo-oracle
    # program cache) shared by both phases — fleets are views over it

    # ---- A) 3-replica chaos failover -------------------------------
    clock = TickClock()
    fleet = build_fleet(
        eng, replicas=3, clock=clock,
        chaos={"enabled": True, "seed": 1, "kill_replica": "r1"})
    # warmup: cover every chunk bucket on every replica path; no kill
    drive(fleet, traffic(6, seed=11))
    warm_compiles = {n: e.compiles for n, e in fleet.replicas.items()}
    total_warm = sum(warm_compiles.values())

    # main traffic: kill r1 four iterations in, mid-prefill/decode
    reqs = traffic(12, seed=23)
    rids, done = drive(fleet, reqs, kill_after=4)

    assert fleet.chaos.injected, "chaos kill never fired"
    assert "r1" not in fleet.replicas, "victim still in the fleet"
    # (1) zero loss: every request retired with a terminal status
    missing = [r for r in rids if r not in done]
    assert not missing, f"requests lost in failover: {missing}"
    assert all(done[r].status is RequestStatus.OK for r in rids), \
        {r: done[r].status for r in rids}
    # (2) the failover is visible: requeues counted, attempts bumped
    snap = fleet.metrics_snapshot()
    requeued = int(snap["fleet"].get("Fleet/requeued", 0))
    bumped = [r for r in rids if done[r].attempts > 0]
    assert requeued >= 1 and len(bumped) == requeued, \
        f"requeued={requeued} but {len(bumped)} requests carry attempts"
    # (3) bit-parity vs solo generate, INCLUDING the requeued requests
    for (p, mn, sd), rid in zip(reqs, rids):
        got = np.asarray(done[rid].tokens, np.int32)
        want = solo_oracle(eng, p, mn, sd, max_len)
        assert np.array_equal(got, want[:len(got)]), \
            f"rid {rid} (attempts={done[rid].attempts}) diverged"
    # (4) survivors' compile counters FROZEN through kill + requeue
    for n, e in fleet.replicas.items():
        assert e.compiles == warm_compiles[n], \
            f"replica {n} compiled {e.compiles - warm_compiles[n]} new " \
            "programs during failover"
    # (5) warm join: a replica added now compiles NOTHING and serves
    joined = fleet.add_replica()
    jr, jdone = drive(fleet, traffic(6, seed=31))
    je = fleet.replicas[joined]
    assert je.compiles == 0, \
        f"joined replica compiled {je.compiles} programs"
    assert je.stats.snapshot()["retired"] >= 1, \
        "joined replica never received traffic"
    gp = fleet.fleet_goodput()
    # (6) requeue attribution: kill → re-admission lands in its OWN
    # Serve/requeue_delay_s histogram, one observation per requeue (so
    # TTFT and failover delay stay separable in the request log)
    rq_delays = sum(int(e.stats.registry.snapshot()["histograms"]
                        .get("Serve/requeue_delay_s", {}).get("count", 0))
                    for e in fleet.replicas.values())
    assert rq_delays == requeued, \
        f"requeue_delay_s observations {rq_delays} != requeued {requeued}"
    # tracing stayed DISABLED in this phase: no fleet ring, no audit —
    # and the compile counters above already pinned the program set
    assert fleet.spans is None and fleet.route_audit() == []
    res["failover"] = {
        "replicas": 3, "requests": len(rids), "requeued": requeued,
        "kills": int(snap["fleet"].get("Fleet/replica_kills", 0)),
        "lost": 0, "warm_compiles_total": total_warm,
        "survivor_compiles_frozen": True,
        "joined_replica_compiles": je.compiles,
        "requeue_delay_observations": rq_delays,
        "fleet_goodput_frac": (round(gp["goodput_frac"], 4)
                               if gp and gp["goodput_frac"] is not None
                               else None),
    }
    fleet.close()

    # ---- B) disaggregated chaos run + distributed tracing ----------
    # prefill replica + 2 decode replicas, tracing ON, one decode
    # replica killed mid-decode: the acceptance scenario for the
    # fleet-wide trace (hops sum to e2e, merged trace w/ cross-replica
    # flows, a route-audit entry behind every decision)
    from deepspeed_tpu.observability import validate_chrome_trace

    clock2 = TickClock()
    fl2 = build_fleet(eng, replicas=3, prefill_replicas=1, clock=clock2,
                      page_size=8, spans=True)
    sys_p = np.random.default_rng(7).integers(0, 256, (16,)).astype(np.int32)
    rng = np.random.default_rng(5)
    prompts = [np.concatenate([sys_p, rng.integers(0, 256, (k,))
                               .astype(np.int32)])
               for k in (4, 7, 4, 7, 4, 7)]
    rids2 = [fl2.submit(p, 5, seed=200 + i, session_id=f"s{i % 3}")
             for i, p in enumerate(prompts)]
    done2 = {}
    killed = False
    it = 0
    while len(done2) < len(rids2):
        for req in fl2.step():
            done2[req.rid] = req
        if not killed and "d1" in fl2.replicas \
                and fl2.replicas["d1"].sched.running:
            # d1 is decoding a handed-off request: kill it NOW — its
            # residents requeue through prefill and hand off again
            fl2.kill_replica("d1")
            killed = True
        it += 1
        assert it < 100_000
    assert killed, "d1 never held a decoding request — kill never fired"
    requeued2 = int(fl2.registry.snapshot()["counters"]
                    .get("Fleet/requeued", 0))
    assert requeued2 >= 1, "the kill orphaned nothing"
    for i, (p, rid) in enumerate(zip(prompts, rids2)):
        got = np.asarray(done2[rid].tokens, np.int32)
        want = solo_oracle(eng, p, 5, 200 + i, max_len)
        assert np.array_equal(got, want[:len(got)]), \
            f"disaggregated rid {rid} diverged from solo generate " \
            f"(attempts={done2[rid].attempts})"
    snap2 = fl2.metrics_snapshot()
    handoffs = int(snap2["fleet"].get("Fleet/handoffs", 0))
    imports = int(snap2["fleet"].get("Fleet/handoff_imports", 0))
    assert handoffs >= 1 and imports == handoffs, \
        f"handoffs={handoffs} imports={imports}"
    # role separation is real: prefill replicas never decode, decode
    # replicas never prefill
    for n, e in fl2.replicas.items():
        s = e.stats.snapshot()
        if fl2.roles[n] == "prefill":
            assert s["decode_steps"] == 0, f"{n} ran decode steps"
        else:
            assert s["prefill_chunks"] == 0, f"{n} ran prefill chunks"
    saved = sum(e.pool.snapshot()["prefill_tokens_saved"]
                for n, e in fl2.replicas.items()
                if fl2.roles[n] == "prefill")
    # (t1) hop sum-to-e2e invariant: every completed request's non-null
    # hops tile [submit, finish] — within 1% on the fake clock
    worst_err = 0.0
    with_handoff = 0
    hop_keys = ("queue_wait", "prefill", "handoff_wait", "import",
                "decode")
    for rid in rids2:
        tr = fl2.request_trace(rid)
        assert tr is not None, f"request_trace({rid}) unknown"
        hops = tr["hops"]
        total = sum(hops[f"{k}_s"] or 0.0 for k in hop_keys)
        assert hops["e2e_s"] and hops["e2e_s"] > 0
        err = abs(total - hops["e2e_s"]) / hops["e2e_s"]
        assert err <= 0.01, f"rid {rid}: hops {total} vs e2e " \
            f"{hops['e2e_s']} ({err:.2%})"
        worst_err = max(worst_err, err)
        if hops["handoff_wait_s"] is not None:
            with_handoff += 1
    assert with_handoff >= 1, "no request carried handoff hops"
    # (t2) route audit: every routing decision is explained — ranked
    # candidates with per-replica exclusion reasons behind each rid
    for rid in rids2:
        audit = fl2.route_audit(rid)
        assert audit, f"rid {rid} has no route-audit entry"
        assert all(e["candidates"] for e in audit), rid
    kill_moves = [e for e in fl2.route_audit()
                  if e["event"] in ("requeue", "requeue_shed")]
    assert len(kill_moves) == requeued2
    # (t3) ONE merged Chrome trace: replicas as pids, request hops
    # stitched into cross-replica flows, schema-valid
    merged = fl2.merge_trace()
    problems = validate_chrome_trace(merged)
    assert problems == [], problems
    evs = merged["traceEvents"]
    flow = [e for e in evs if e["ph"] in ("s", "t", "f")]
    pids = sorted({e["pid"] for e in evs if e["ph"] != "M"})
    assert flow, "merged trace has no flow events"
    assert len({e["pid"] for e in flow}) >= 2, \
        "flows never crossed a replica boundary"
    assert len(pids) >= 3, f"expected router + >=2 replica pids: {pids}"
    trace_out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "FLEET_TRACE.json")
    with open(trace_out, "w") as f:
        json.dump(merged, f)
    res["disaggregated"] = {
        "replicas": 3, "prefill_replicas": 1, "requests": len(rids2),
        "handoffs": handoffs, "handoff_imports": imports,
        "parity_with_solo": True, "decode_replica_killed": True,
        "requeued": requeued2,
        "prefill_tokens_saved_at_source": int(saved),
    }
    res["tracing"] = {
        "hop_sum_worst_rel_err": round(worst_err, 6),
        "requests_with_handoff_hops": with_handoff,
        "route_audit_entries": len(fl2.route_audit()),
        "merged_trace_valid": True,
        "merged_trace_events": len(evs),
        "flow_events": len(flow), "pids": pids,
        "trace_file": "FLEET_TRACE.json",
    }
    fl2.close()

    res["verdict"] = "smoke-pass"
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "FLEET_BENCH.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res))


# ------------------------------------------------------------------- main
def main():
    """Fuller (still CPU-sized) run: bigger traffic, per-replica routing
    spread, goodput rollup — written to FLEET_BENCH.json."""
    clock = TickClock()
    eng = build_engine()
    fleet = build_fleet(
        eng, replicas=3, clock=clock,
        chaos={"enabled": True, "seed": 1, "kill_replica": "r1"})
    drive(fleet, traffic(9, seed=11))                       # warmup
    reqs = traffic(36, seed=23)
    rids, done = drive(fleet, reqs, kill_after=10)
    snap = fleet.metrics_snapshot()
    gp = fleet.fleet_goodput()
    res = {
        "workload": {"replicas": 3, "requests": len(rids),
                     "slots_per_replica": 2, "max_len": 48,
                     "prefill_chunk": 16},
        "completed": sum(1 for r in rids if r in done),
        "requeued": int(snap["fleet"].get("Fleet/requeued", 0)),
        "kills": int(snap["fleet"].get("Fleet/replica_kills", 0)),
        "routed": {n: int(v) for n, v in snap["fleet"].items()
                   if n.startswith("Fleet/routed_")},
        "per_replica": {n: {"compiles": r["compiles"],
                            "retired": r["retired"],
                            "decode_steps": r["decode_steps"]}
                        for n, r in snap["replicas"].items()},
        "fleet_goodput": gp,
    }
    fleet.close()
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "FLEET_BENCH.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        smoke()
    else:
        main()
