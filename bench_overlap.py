"""Quantized + overlapped collectives bench: the thing commscope priced.

Full mode (bench_all chain, TPU with CPU fallback): train the fused-fp
grad spelling vs the bucketed-overlap int8 spelling and measure step
wall, run TP decode with the fp psum vs the two-sided int8 collective
(``inference.tp_comm_quant``) and measure tokens/s, and land the
commscope on/off rows — ``Comm/exposed_frac`` + per-kind busbw from
``engine.comm_observatory()`` for BOTH spellings — into
``OVERLAP_BENCH.json``, a ``grad_overlap`` section in
``COMMSCOPE_BENCH.json``, and an ``overlap`` section in the newest
``MULTICHIP_r0*.json`` (perf_ledger tracks ``exposed``/``step_time``
down-is-good, wire ratio down-is-good). On a CPU backend the profiler
has no device op timeline, so the time-anatomy columns are null —
recorded, never faked; the static wire-byte columns are exact either
way.

``--smoke`` is the CPU tier-1 gate (wired via
tests/unit/test_overlap_bench.py):

1. fake-trace seam: a fused-spelling trace (grad collective serialized
   after the backward) vs an overlapped trace (same collective seconds
   riding concurrent compute) decompose to EXACTLY the known exposed
   fractions — the measured exposed-fraction DROP the overlap buys;
2. parity oracles: bucketed fp grads bitwise == the fused flat fp
   spelling (losses AND params), int8 overlap converges with
   error-feedback residuals carried, the two-sided int8 psum lands
   within blockwise-quantization error of the exact sum (end-to-end
   quantized-TP-decode greedy parity incl. TP=4 is pinned by
   tests/unit/test_tp_quant.py, which tier-1 runs beside this gate);
3. zero new steady-state programs with every feature disabled: a
   default engine and one with the knobs explicitly off compile the
   same program set and emit bit-identical losses/tokens;
4. the int8 spelling's compiled wire bytes land within 2% of the static
   plan summary and under half the fp32 flat equivalent.

Prints one JSON line ending in "smoke-pass"; exits nonzero on failure.
"""

import json
import os
import sys
import tempfile
import time

_CHILD_MARK = "_DSTPU_OVERLAP_CHILD"
_ROOT = os.path.dirname(os.path.abspath(__file__))
_OUT = os.path.join(_ROOT, "OVERLAP_BENCH.json")


# ------------------------------------------------------------- fake traces
def make_fused_trace(n_steps=3, step_ms=100.0, devices=2):
    """Known anatomy per 100ms step, the FUSED grad spelling: backward
    compute [0,60), ONE flat all-reduce [60,90) serialized after it →
    exposed 30ms, exposed_frac 0.3."""
    return _trace(n_steps, step_ms, devices, (
        (0.0, 60e3, "fusion.bwd"),
        (60e3, 30e3, "all-reduce.grads"),
    )), 0.3


def make_overlap_trace(n_steps=3, step_ms=100.0, devices=2):
    """Same collective seconds, BUCKETED overlap: compute [0,60) and
    [65,95); bucket a2a [20,35) fully overlapped, bucket a2a [55,70)
    exposed only [60,65), gather [95,100) exposed → 10ms exposed,
    exposed_frac 0.1."""
    return _trace(n_steps, step_ms, devices, (
        (0.0, 60e3, "fusion.bwd"),
        (65e3, 30e3, "fusion.bwd.tail"),
        (20e3, 15e3, "all-to-all.bucket0"),
        (55e3, 15e3, "all-to-all.bucket1"),
        (95e3, 5e3, "all-gather.bucket1"),
    )), 0.1


def _trace(n_steps, step_ms, devices, ops):
    evs = []
    for d in range(devices):
        pid = 10 + d
        evs.append({"ph": "M", "name": "process_name", "pid": pid,
                    "args": {"name": f"/device:TPU:{d}"}})
        for s in range(n_steps):
            base = s * step_ms * 1e3
            for ts, dur, name in ops:
                evs.append({"ph": "X", "pid": pid, "tid": 1,
                            "ts": base + ts, "dur": dur,
                            "name": f"{name}.{s}"})
    windows = [(s * step_ms * 1e-3, (s + 1) * step_ms * 1e-3)
               for s in range(n_steps)]
    return {"traceEvents": evs}, windows


# ---------------------------------------------------------------- builders
def build_train(mode=None, overlap=False, bucket=0, commscope=False,
                trace_dir=None, seed=3, stage=2):
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, tiny_test

    n = len(jax.devices())
    cfg = {
        "train_batch_size": max(8, n),
        "optimizer": {"type": "adamw", "params": {"lr": 2e-3}},
        "zero_optimization": {"stage": stage},
        "mesh": {"data": n},
        "seed": seed,
    }
    if mode:
        cfg["gradient_compression"] = {"enabled": True, "type": mode,
                                       "overlap": overlap,
                                       "bucket_elems": bucket}
    if commscope:
        obs = {"commscope": {"enabled": True}}
        if trace_dir:
            obs.update({"trace_steps": [4, 6], "trace_dir": trace_dir})
        cfg["observability"] = obs
    return ds.initialize(cfg, build_model(tiny_test()))


def train_batchset(size=8):
    from deepspeed_tpu.runtime.dataloader import (DataLoader,
                                                  random_token_dataset)

    data = random_token_dataset(size, 32, 256, learnable=True)
    return DataLoader(data, local_batch_size=size,
                      shuffle=False).collate_fn(data[:size])


def trained_tiny(steps=16, seed=4):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, tiny_test
    from deepspeed_tpu.runtime.dataloader import (DataLoader,
                                                  random_token_dataset)

    n = len(jax.devices())
    bs = max(8, n)
    model = build_model(tiny_test(max_seq=64, dtype=jnp.float32))
    eng = ds.initialize({
        "train_batch_size": bs,
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "mesh": {"data": n}, "seed": 0}, model)
    data = random_token_dataset(8 * bs, 32, 256, learnable=True, seed=seed)
    dl = DataLoader(data, local_batch_size=bs, shuffle=False)
    batches = [dl.collate_fn(data[i * bs:(i + 1) * bs]) for i in range(8)]
    for i in range(steps):
        eng.train_batch(batches[i % len(batches)])
    params = jax.tree.map(lambda a: np.asarray(a, np.float32),
                          eng.state.master_params)
    prompts = [np.asarray(data[i]["input_ids"][:p], np.int32)
               for i, p in enumerate((9, 21, 5))]
    return model, params, prompts


# ------------------------------------------------------------------ smoke
def smoke():
    # the smoke is the CPU tier-1 gate: force the 8-device host platform
    # (the tests' conftest does the same) so the data-parallel oracles
    # exercise real collectives. Must run before jax is first imported.
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.comm.hlo_analysis import collective_summary
    from deepspeed_tpu.observability.commscope import (CommScope,
                                                       CommScopeConfig)

    # (1) fake-trace seam: the overlap spelling's measured
    # exposed-fraction DROP, exact by construction
    fracs = {}
    for name, (payload, want) in (
            ("fused", make_fused_trace()),
            ("overlap", make_overlap_trace())):
        trace, windows = payload
        cs = CommScope(CommScopeConfig(enabled=True), n_devices=2)
        rep = cs.analyze(trace, windows=windows, peak_ici_gbps=300.0)
        an = rep["anatomy"]
        tile = an["compute_s"] + an["exposed_collective_s"] + an["other_s"]
        assert abs(tile - an["wall_s"]) <= 0.01 * an["wall_s"]
        assert abs(an["exposed_comm_frac"] - want) < 1e-9, \
            (name, an["exposed_comm_frac"], want)
        fracs[name] = an["exposed_comm_frac"]
    drop = fracs["fused"] - fracs["overlap"]
    assert abs(drop - 0.2) < 1e-9, fracs

    # (2a) parity oracle: bucketed fp == fused flat fp, bitwise
    b = train_batchset()
    fused = build_train("fp")
    bucketed = build_train("fp", overlap=True, bucket=2000)
    assert len(bucketed._grad_plan.buckets) > 1
    lf = [float(fused.train_batch(b)["loss"]) for _ in range(3)]
    lb = [float(bucketed.train_batch(b)["loss"]) for _ in range(3)]
    assert lf == lb, (lf, lb)
    for x, y in zip(jax.tree.leaves(fused.state.master_params),
                    jax.tree.leaves(bucketed.state.master_params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    # (2b) int8 overlap converges, residuals carried
    q = build_train("int8", overlap=True, bucket=2000)
    ql = [float(q.train_batch(b)["loss"]) for _ in range(5)]
    assert ql[-1] < ql[0], ql
    assert float(np.abs(np.asarray(
        q.state.comm_err["worker"])).max()) > 0.0

    # (2c) quantized TP psum: the int8 two-sided all-reduce is accurate
    # vs the exact sum (the decode-step collective's primitive oracle;
    # END-TO-END greedy token parity incl. TP=4 on a trained model is
    # pinned by tests/unit/test_tp_quant.py, which tier-1 runs beside
    # this gate — not duplicated here to keep the smoke inside budget)
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.comm.compressed import int8_psum
    from deepspeed_tpu.platform.mesh import MeshSpec, build_mesh

    mesh8 = build_mesh(MeshSpec(data=8))
    xs = np.random.default_rng(7).normal(size=(8, 4, 96)).astype(np.float32)
    fn = jax.jit(jax.shard_map(
        lambda v: int8_psum(v[0], "data")[None], mesh=mesh8,
        axis_names=frozenset({"data"}), in_specs=P("data"),
        out_specs=P("data"), check_vma=False))
    with mesh8:
        got = np.asarray(fn(xs))[0]
    exact = xs.sum(axis=0)
    err = float(np.abs(got - exact).max())
    assert err < 0.05 * max(1.0, float(np.abs(exact).max())), err

    # (3) zero new steady-state programs with the features disabled: an
    # engine with the knob explicitly off compiles the same program set
    # and emits bit-identical tokens as one that never heard of it (the
    # training-side freeze is the whole pre-existing tier-1 suite
    # running the untouched default path bit-for-bit)
    from deepspeed_tpu.models import build_model, tiny_test

    model = build_model(tiny_test(max_seq=64, dtype="float32"))
    params = jax.tree.map(lambda a: np.asarray(a),
                          jax.jit(model.init)(jax.random.PRNGKey(0)))
    prompt = np.random.default_rng(9).integers(
        2, 256, (12,)).astype(np.int32)
    e_off = ds.init_inference(model, params,
                              {"dtype": "float32", "eos_token_id": 1,
                               "tp_comm_quant": 0})
    e_def = ds.init_inference(model, params,
                              {"dtype": "float32", "eos_token_id": 1})
    a = np.asarray(e_off.generate(np.asarray(prompt[None]), 6,
                                  greedy=True, request_seeds=[1],
                                  cache_len=64))
    c = np.asarray(e_def.generate(np.asarray(prompt[None]), 6,
                                  greedy=True, request_seeds=[1],
                                  cache_len=64))
    np.testing.assert_array_equal(a, c)
    assert len(e_off._gen_cache) == len(e_def._gen_cache)

    # (4) the compiled int8 wire matches the static plan and halves the
    # fp32 flat equivalent
    # stage 0 so the grad hops are the ONLY a2a/all-gather in the
    # program (stage >= 2 adds the ZeRO master->compute param gather)
    q0 = build_train("int8", overlap=True, bucket=4000, stage=0)
    g = q0._make_global(b)
    with q0.mesh:
        hlo = q0._train_step.lower(q0.state, g).compile().as_text()
    summ = collective_summary(hlo)
    got = sum(summ.get(k, {"mbytes": 0.0})["mbytes"]
              for k in ("all-to-all", "all-gather"))
    wire = q0.grad_comm_summary()
    want = wire["wire_mbytes_per_step"]
    assert abs(got - want) <= 0.02 * want, (got, want)
    # vs the UNPADDED fp32 flat all-reduce: the dtype floor is ~0.501
    # (2 int8 hops + scale planes / 4 bytes); the toy model's buckets
    # sit near the world*BLOCK padding quantum, so CPU-smoke scale pays
    # ~6 pts of padding on top (real-scale plans amortize it away)
    assert 0.50 <= wire["wire_ratio"] < 0.60, wire

    print(json.dumps({
        "smoke": True,
        "exposed_frac_fused": fracs["fused"],
        "exposed_frac_overlap": fracs["overlap"],
        "measured_exposed_drop": drop,
        "fp_overlap_bit_identical": True,
        "int8_losses": ql,
        "int8_psum_max_abs_err": err,
        "wire_mbytes_per_step": wire["wire_mbytes_per_step"],
        "wire_ratio_vs_fp32": wire["wire_ratio"],
        "verdict": "smoke-pass",
    }))


# ------------------------------------------------------------------- full
def _median(xs):
    s = sorted(xs)
    return s[len(s) // 2]


def _run_child():
    import jax
    import numpy as np

    import deepspeed_tpu as ds

    platform = jax.devices()[0].platform
    t0 = time.time()
    n_dev = len(jax.devices())
    b = train_batchset(max(8, n_dev))

    def step_time(eng, steps=8, warm=3):
        for _ in range(warm):
            eng.train_batch(b)
        walls = []
        for _ in range(steps):
            s = time.perf_counter()
            eng.train_batch(b)
            jax.block_until_ready(eng.state.step)
            walls.append(time.perf_counter() - s)
        return _median(walls)

    rows = {}
    for name, kw in (("fused_fp", dict(mode="fp")),
                     ("overlap_int8", dict(mode="int8", overlap=True,
                                           bucket=4000))):
        tdir = tempfile.mkdtemp(prefix=f"overlap_bench_{name}_")
        eng = build_train(commscope=True, trace_dir=tdir, **kw)
        wall = step_time(eng)
        rep = eng.comm_observatory(n_steps=3)
        an, led = rep["anatomy"], rep["ledger"]
        rows[name] = {
            "step_time_s": wall,
            "wire": eng.grad_comm_summary(),
            "exposed_comm_frac": an["exposed_comm_frac"],
            "overlap_frac": an["overlap_frac"],
            "busbw_gbps": {k: v["busbw_gbps"]
                           for k, v in led["by_kind"].items()},
            "wire_mbytes_by_kind": {k: v["mbytes_per_step"]
                                    for k, v in led["by_kind"].items()},
        }
        eng.close()

    # TP decode: fp psum vs int8 two-sided wire, tokens/s
    model, params, prompts = trained_tiny()
    tp = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    decode_rows = {}
    if tp > 1:
        base = {"dtype": "float32", "eos_token_id": 1,
                "tensor_parallel": tp}
        for name, extra in (("fp_psum", {}),
                            ("int8_psum", {"tp_comm_quant": 8})):
            eng = ds.init_inference(model, params, {**base, **extra})
            p = prompts[1]
            # warm compile, then timed greedy decode
            eng.generate(np.asarray(p[None]), 16, greedy=True,
                         request_seeds=[5], cache_len=64)
            s = time.perf_counter()
            reps = 6
            for r in range(reps):
                out = eng.generate(np.asarray(p[None]), 16, greedy=True,
                                   request_seeds=[5 + r], cache_len=64)
            np.asarray(out)
            dt = (time.perf_counter() - s) / reps
            decode_rows[name] = {"tokens_per_s": 16 / dt,
                                 "wall_s_per_request": dt}
        parity = np.array_equal(
            np.asarray(ds.init_inference(model, params, base).generate(
                np.asarray(prompts[0][None]), 8, greedy=True,
                request_seeds=[3], cache_len=64)),
            np.asarray(ds.init_inference(
                model, params, {**base, "tp_comm_quant": 8}).generate(
                np.asarray(prompts[0][None]), 8, greedy=True,
                request_seeds=[3], cache_len=64)))
    else:
        parity = None

    fused = rows["fused_fp"]
    over = rows["overlap_int8"]
    ratio = over["wire"]["wire_ratio"]
    out = {
        "metric": "quantized_overlapped_collectives",
        # headline value is the wire COMPRESSION factor (up-is-good in
        # the perf ledger's "value" convention); the raw ratio rides in
        # wire_ratio_vs_fp32 (down-is-good)
        "value": (1.0 / ratio) if ratio else None,
        "unit": "grad wire compression factor vs fp32 flat equivalent "
                f"(platform={platform}"
                + ("" if platform == "tpu" else ", CPU-FALLBACK: no "
                   "device op timeline — exposed/busbw columns null")
                + ")",
        "platform": platform,
        "n_devices": n_dev,
        "train": rows,
        "step_time_fused_fp_s": fused["step_time_s"],
        "step_time_overlap_int8_s": over["step_time_s"],
        "wire_ratio_vs_fp32": over["wire"]["wire_ratio"],
        "decode_tp": tp,
        "decode": decode_rows,
        "tp_quant_greedy_parity": parity,
        "seconds": round(time.time() - t0, 1),
        "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    print(json.dumps(out), flush=True)


def _patch_artifacts(result: dict) -> None:
    """Land the on/off rows beside the PR-12 artifacts: a
    ``grad_overlap`` section in COMMSCOPE_BENCH.json and an ``overlap``
    section in the newest MULTICHIP_r0*.json (numeric round order)."""
    import glob
    import re

    section = {
        "exposed_comm_frac_fused": (result.get("train", {})
                                    .get("fused_fp", {})
                                    .get("exposed_comm_frac")),
        "exposed_comm_frac_overlap": (result.get("train", {})
                                      .get("overlap_int8", {})
                                      .get("exposed_comm_frac")),
        "busbw_gbps_overlap": (result.get("train", {})
                               .get("overlap_int8", {})
                               .get("busbw_gbps")),
        "wire_ratio_vs_fp32": result.get("wire_ratio_vs_fp32"),
        "step_time_fused_fp_s": result.get("step_time_fused_fp_s"),
        "step_time_overlap_int8_s": result.get("step_time_overlap_int8_s"),
        "platform": result.get("platform"),
    }
    cs = os.path.join(_ROOT, "COMMSCOPE_BENCH.json")
    try:
        with open(cs, encoding="utf-8") as f:
            obj = json.load(f)
        if isinstance(obj, dict):
            obj["grad_overlap"] = section
            with open(cs, "w", encoding="utf-8") as f:
                json.dump(obj, f, indent=2)
            print(f"[overlap] wrote grad_overlap section into {cs}",
                  flush=True)
    except (OSError, json.JSONDecodeError):
        pass

    def round_no(p):
        m = re.search(r"_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    cands = sorted(glob.glob(os.path.join(_ROOT, "MULTICHIP_r*.json")),
                   key=round_no)
    if not cands:
        return
    path = cands[-1]
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError):
        return
    if not isinstance(obj, dict):
        return
    obj["overlap"] = section
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=2)
    print(f"[overlap] wrote overlap section into {path}", flush=True)


def main():
    import bench_common as bc

    if os.environ.get(_CHILD_MARK) == "1":
        _run_child()
        return
    env = dict(os.environ)
    env[_CHILD_MARK] = "1"
    # multi-device collectives are the whole subject: give the child a
    # multi-device host platform (affects the CPU backend only — a real
    # TPU's device count is the hardware's)
    flags = env.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    me = os.path.abspath(__file__)
    window_s = float(os.environ.get("DSTPU_BENCH_WINDOW_S", 10 * 60))
    result = bc.run_with_tpu_window(me, env, window_s=window_s,
                                    child_timeout=600, tag="overlap")
    if result is None:
        bc.log("TPU unavailable; measuring on CPU (exposed/busbw columns "
               "will be null — no device op timeline)", "overlap")
        result = bc.run_child(me, bc.cpu_fallback_env(env, n_devices=8),
                              timeout=600, tag="overlap")
    if result is None:
        raise SystemExit("overlap bench failed on TPU and CPU")
    with open(_OUT, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result), flush=True)
    _patch_artifacts(result)


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        smoke()
    else:
        main()
