"""RLHF-style loop with the hybrid engine (BASELINE config 5's shape).

The reference's DeepSpeed-Chat flow: an actor that alternates rollout
generation (inference path) and policy updates (ZeRO training path) over
the SAME weights — the DeepSpeedHybridEngine's whole reason to exist
(reference ``runtime/hybrid_engine.py:32``). Here both are jitted
functions over one sharded master tree, so the loop is just:

    rollout  = actor.generate(prompts)       # live training params
    rewards  = reward_model(rollout)
    update   = actor.train_batch(weighted)   # reward-filtered finetuning

The "reward model" is synthetic (prefers even token ids) so the example is
self-contained; the update is best-of rejection finetuning (train only on
above-median-reward rollouts) — the simplest RLHF-shaped objective. (A
tiny random model + a few iterations only nudges the reward; the point is
the loop mechanics, not convergence.)

Run: DSTPU_EXAMPLE_SMOKE=1 python examples/rlhf_hybrid.py
"""

import numpy as np

from deepspeed_tpu.models import build_model, tiny_test
from deepspeed_tpu.runtime.hybrid_engine import HybridEngine

actor = HybridEngine({
    "train_batch_size": 8,
    "optimizer": {"type": "adamw", "params": {"lr": 2e-2}},
    "zero_optimization": {"stage": 2},
}, build_model(tiny_test(max_seq=64)), eos_token_id=None)

rng = np.random.default_rng(0)
prompts = rng.integers(0, 256, (8, 8), dtype=np.int32)


def reward_fn(tokens: np.ndarray) -> np.ndarray:
    """Synthetic preference: fraction of even token ids per rollout."""
    return (tokens % 2 == 0).mean(axis=1)


base = reward_fn(np.asarray(actor.generate(prompts, 16, greedy=True)))
for it in range(10):
    new = np.asarray(actor.generate(prompts, 16, temperature=1.0))
    rewards = reward_fn(new)
    keep = rewards >= np.median(rewards)           # best-of filtering
    rollouts = np.concatenate([prompts, new], axis=1)
    # train only on the kept rollouts' generated region
    mask = np.zeros_like(rollouts)
    mask[:, prompts.shape[1]:] = keep[:, None]
    batch = {"input_ids": rollouts.astype(np.int32),
             "loss_mask": mask.astype(np.int32)}
    metrics = actor.train_batch(batch)
    print(f"iter {it}: mean reward {rewards.mean():.3f} "
          f"(kept {int(keep.sum())}/8) loss {metrics['loss']:.4f}",
          flush=True)

final = reward_fn(np.asarray(actor.generate(prompts, 16, greedy=True)))
print(f"greedy reward: before {base.mean():.3f} -> after {final.mean():.3f}")
