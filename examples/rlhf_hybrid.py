"""RLHF PPO loop with LoRA adapters on the hybrid engine (BASELINE cfg 5).

The reference's DeepSpeed-Chat actor step (``blogs/deepspeed-chat/
README.md:41`` + ``runtime/hybrid_engine.py:32``): rollouts generate
through the inference path over the SAME weights the ZeRO training path
updates, LoRA adapters are the only trainable params
(``only_optimize_lora``), and the objective is PPO's clipped policy ratio
with a KL penalty against the rollout policy. TPU-native, that is:

    old_logp = actor.token_logprobs(rollouts)        # policy snapshot
    rollout  = actor.generate(prompts)               # LoRA merged in-jit
    update   = actor.train_batch({ppo keys...})      # adapters-only step

The "reward model" is synthetic (prefers even token ids) so the example is
self-contained. A tiny random model + a few iterations only nudges the
reward; the point is the loop mechanics: LoRA-frozen base, PPO objective,
merged-weight generation.

Run: DSTPU_EXAMPLE_SMOKE=1 python examples/rlhf_hybrid.py
"""

import jax
import numpy as np

from deepspeed_tpu.models import build_model, tiny_test
from deepspeed_tpu.runtime.hybrid_engine import HybridEngine

actor = HybridEngine({
    "train_batch_size": 8,
    "optimizer": {"type": "adamw", "params": {"lr": 5e-3,
                                              "weight_decay": 0.01}},
    "zero_optimization": {"stage": 2},
    "lora": {"enabled": True, "rank": 4, "alpha": 8.0},
}, build_model(tiny_test(max_seq=64)), eos_token_id=None)

base_snapshot = jax.tree.map(np.asarray,
                             actor.state.master_params["layers"])

rng = np.random.default_rng(0)
prompts = rng.integers(0, 256, (8, 8), dtype=np.int32)
P = prompts.shape[1]


def reward_fn(tokens: np.ndarray) -> np.ndarray:
    """Synthetic preference: fraction of even token ids per rollout."""
    return (tokens % 2 == 0).mean(axis=1)


base = reward_fn(np.asarray(actor.generate(prompts, 16, greedy=True)))
for it in range(8):
    new = np.asarray(actor.generate(prompts, 16, temperature=1.0))
    rollouts = np.concatenate([prompts, new], axis=1).astype(np.int32)
    rewards = reward_fn(new)
    adv = (rewards - rewards.mean()) / (rewards.std() + 1e-6)

    # PPO: snapshot the rollout policy's log-probs, then update against it
    old_logp = np.asarray(actor.token_logprobs(rollouts))
    mask = np.zeros_like(rollouts, np.float32)
    mask[:, P:] = 1.0                      # optimize the generated region
    batch = {"input_ids": rollouts,
             "loss_mask": mask,
             "ppo_old_logp": old_logp,
             "ppo_advantage": adv.astype(np.float32)}
    # several PPO epochs against ONE snapshot: after the first update the
    # ratio departs from 1 and the clip + KL terms engage
    for _ in range(3):
        metrics = actor.train_batch(dict(batch))
    print(f"iter {it}: mean reward {rewards.mean():.3f} "
          f"ppo loss {metrics['loss']:.4f}", flush=True)

final = reward_fn(np.asarray(actor.generate(prompts, 16, greedy=True)))
print(f"greedy reward: before {base.mean():.3f} -> after {final.mean():.3f}")

# the base stayed frozen: every update went through the adapters
after = jax.tree.map(np.asarray, actor.state.master_params["layers"])
drift = max(float(np.abs(a - b).max())
            for a, b in zip(jax.tree.leaves(after),
                            jax.tree.leaves(base_snapshot)))
print(f"frozen-base max drift: {drift:.2e} (adapters-only training)")
assert drift == 0.0
