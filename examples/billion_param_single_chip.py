"""Train a 1.00 B-param decoder on ONE 16 GiB TPU chip — the measured
round-5 recipe (GPT_LARGE_BENCH.json: ~0.33-0.45 MFU depending on
attention path; see docs/TUNING.md "Remat").

The three knobs that make 1 B fit and run fast on a single v5e:

1. ``remat save_names`` — saves only the tagged layer-boundary residuals
   (~4x less HBM than dots_saveable; the difference between fitting and
   an 18.3 GiB compile).
2. Lion — one fp32 moment (14 bytes/param total vs AdamW's 18; 1.004 B
   params x 14 = 14.1 GiB, leaving room for activations).
3. flash attention at the block-512 default — bf16 operands on the MXU
   and wide tiles (measured: 305.5 ms/step vs 410.5 for XLA attention).

Run (single chip):  python examples/billion_param_single_chip.py
Smallest smoke:     DSTPU_EXAMPLE_SMOKE=1 python examples/billion_param_single_chip.py
"""

import os

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model, gpt2, tiny_test
from deepspeed_tpu.ops.flash_attention import make_flash_attention
from deepspeed_tpu.runtime.dataloader import (DataLoader, RepeatingLoader,
                                              random_token_dataset)

SMOKE = os.environ.get("DSTPU_EXAMPLE_SMOKE") == "1"

config = {
    # mbs 4 at seq 1024: the largest micro-batch the save_names policy
    # fits beside 14.1 GiB of param state on a 16 GiB chip
    "train_batch_size": 8 if SMOKE else 4,
    "train_micro_batch_size_per_gpu": "auto" if SMOKE else 4,
    "optimizer": {"type": "lion", "params": {"lr": 1e-4}},
    "gradient_clipping": 1.0,
    "zero_optimization": {"stage": 1},
    "remat": {"enabled": True, "policy": "save_names"},
    "steps_per_print": 5,
}

# GPT-2-XL width at 30 layers = 1.004 B params
model_cfg = (tiny_test(max_seq=64) if SMOKE else
             gpt2("1.5b", n_layer=30, max_seq=1024))
model = build_model(model_cfg, attention_fn=make_flash_attention())
engine = ds.initialize(config, model)

data = random_token_dataset(2 * engine.train_batch_size,
                            seq_len=model_cfg.max_seq,
                            vocab_size=model_cfg.vocab_size, learnable=True)
loader = DataLoader(data, local_batch_size=engine.train_batch_size)

steps = 4 if SMOKE else 1000
it = iter(RepeatingLoader(loader))
for step in range(steps):
    metrics = engine.train_batch(next(it))
print(f"final loss {float(metrics['loss']):.4f} over {steps} steps")
