"""The composed deep end: MoE trunk + 1F1B pipeline + elastic schema.

Run under the elastic agent so worker loss relaunches at a new world size
and resumes from the checkpoint:

    dstpu_elastic --nproc 1 --max_train_batch_size 32 \
        --micro_batch_sizes 1,2,4 examples/moe_pipeline_elastic.py
"""

import pathlib

import deepspeed_tpu as ds
from deepspeed_tpu.models import tiny_test
from deepspeed_tpu.models.pipeline import build_pipeline_model
from deepspeed_tpu.runtime.dataloader import (DataLoader, RepeatingLoader,
                                              random_token_dataset)

CKPT = "ckpts/moe_pipe"

cfg = tiny_test(n_layer=4, num_experts=2, max_seq=64)
model = build_pipeline_model(cfg, n_stages=2, num_micro=4, schedule="1f1b")
engine = ds.initialize({
    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    "zero_optimization": {"stage": 1},
    "elasticity": {"enabled": True, "max_train_batch_size": 32,
                   "micro_batch_sizes": [1, 2, 4], "max_devices": 64},
    "mesh": {"pipe": 2},
}, model)
if (pathlib.Path(CKPT) / "latest").exists():
    engine.load_checkpoint(CKPT)

data = random_token_dataset(32, seq_len=64, vocab_size=cfg.vocab_size,
                            learnable=True)
loader = DataLoader(data, local_batch_size=engine.train_batch_size)
it = iter(RepeatingLoader(loader))
loss = float("nan")
while engine.global_steps < 8:
    loss = engine.train_batch(dict(next(it)))["loss"]
    engine.save_checkpoint(CKPT)
print(f"done at step {engine.global_steps}" +
      ("" if loss != loss else f", loss {loss:.4f}"))
