"""Pretrain GPT-2 from scratch: ZeRO + mixed precision + checkpoints.

Run (single host):  python examples/pretrain_gpt2.py
Multi-host:         dstpu -H hostfile examples/pretrain_gpt2.py
Smallest smoke:     DSTPU_EXAMPLE_SMOKE=1 python examples/pretrain_gpt2.py
"""

import os

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model, gpt2, tiny_test
from deepspeed_tpu.runtime.dataloader import (DataLoader, RepeatingLoader,
                                              random_token_dataset)

SMOKE = os.environ.get("DSTPU_EXAMPLE_SMOKE") == "1"

config = {
    "train_batch_size": 8 if SMOKE else 256,
    "optimizer": {"type": "adamw",
                  "params": {"lr": 3e-4, "weight_decay": 0.01}},
    "scheduler": {"type": "WarmupDecayLR",
                  "params": {"warmup_num_steps": 10 if SMOKE else 2000,
                             "total_num_steps": 20 if SMOKE else 100000}},
    "gradient_clipping": 1.0,
    "zero_optimization": {"stage": 1},
    "remat": {"enabled": True, "policy": "dots_saveable"},
    "steps_per_print": 5,
}

model_cfg = tiny_test(max_seq=64) if SMOKE else gpt2("125m", max_seq=1024)
engine = ds.initialize(config, build_model(model_cfg))

# Real training would iterate an MMapIndexedDataset; random tokens here.
data = random_token_dataset(4 * engine.train_batch_size,
                            seq_len=model_cfg.max_seq,
                            vocab_size=model_cfg.vocab_size, learnable=True)
loader = DataLoader(data, local_batch_size=engine.train_batch_size)

steps = 6 if SMOKE else 1000
it = iter(RepeatingLoader(loader))
for step in range(steps):
    metrics = engine.train_batch(dict(next(it)))
    if (step + 1) % 3 == 0:
        engine.save_checkpoint("ckpts/gpt2_pretrain")
print(f"final loss {metrics['loss']:.4f} after {steps} steps")
