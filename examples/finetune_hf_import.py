"""Finetune an imported HF checkpoint, then export back to HF format.

The reference does this with kernel injection + zero_to_fp32; here the
checkpoint maps onto the native trunk and sharding comes from the config.
Smoke mode builds a tiny random HF model locally instead of downloading.

Run: DSTPU_EXAMPLE_SMOKE=1 python examples/finetune_hf_import.py
     (or point DSTPU_HF_PATH at a real HF checkpoint directory)
"""

import os

import deepspeed_tpu as ds
from deepspeed_tpu.models import (build_model, export_hf_checkpoint,
                                  import_state_dict, load_hf_checkpoint)
from deepspeed_tpu.runtime.dataloader import (DataLoader, RepeatingLoader,
                                              random_token_dataset)

path = os.environ.get("DSTPU_HF_PATH")
if path:
    cfg, params = load_hf_checkpoint(path)
else:  # smoke: tiny random GPT-2 from transformers, no downloads
    import torch
    import transformers

    torch.manual_seed(0)
    hf_cfg = transformers.GPT2Config(vocab_size=256, n_positions=64,
                                     n_embd=64, n_layer=2, n_head=4)
    hf_model = transformers.GPT2LMHeadModel(hf_cfg)
    cfg, params = import_state_dict(hf_model.state_dict(),
                                    hf_config=hf_cfg.to_dict())

engine = ds.initialize({
    "train_batch_size": 8,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-5}},
    "zero_optimization": {"stage": 2},
}, build_model(cfg), params=params)

data = random_token_dataset(16, seq_len=32, vocab_size=cfg.vocab_size,
                            learnable=True)
loader = DataLoader(data, local_batch_size=engine.train_batch_size)
it = iter(RepeatingLoader(loader))
for _ in range(4):
    metrics = engine.train_batch(dict(next(it)))
print(f"finetuned to loss {metrics['loss']:.4f}")

export_hf_checkpoint(engine.fp32_params(), cfg, "out/finetuned_hf")
print("exported to out/finetuned_hf (config.json + model.safetensors)")
