"""Serve models with init_inference: generation, quantized TP serving,
and feature extraction.

The reference's serving story is ``deepspeed.init_inference`` + kernel
injection (``inference/engine.py``); here the same call shards the trunk
over a TP mesh, optionally weight-only-quantizes it, and compiles the
decode loop per (shape, knobs). Three surfaces:

1. generate() on a causal LM (greedy + sampled),
2. TP=2 sharded serving, and int8 weight-only quantized serving,
3. forward() on a feature tower (CLIP-text-style) -> hidden states.

Run: DSTPU_EXAMPLE_SMOKE=1 JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/serve_inference.py
(on a TPU pod slice, run unmodified — the mesh sizes to the real chips)
"""

import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerConfig, build_model, gpt2

rng = np.random.default_rng(0)

# 1. causal LM generation -------------------------------------------------
import jax

cfg = gpt2("125m", max_seq=64, vocab_size=256, n_layer=2, n_head=4,
           d_model=64)
lm = build_model(cfg)
params = lm.init(jax.random.key(0))
engine = ds.init_inference(lm, params, {"dtype": "float32"})
prompt = rng.integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
out = np.asarray(engine.generate(prompt, max_new_tokens=8, greedy=True))
print(f"greedy continuation shape {out.shape}")
out = np.asarray(engine.generate(prompt, max_new_tokens=8,
                                 temperature=0.8, top_p=0.9))
print(f"sampled continuation shape {out.shape}")

# 2a. TP=2 sharded serving (needs an even device count) -------------------
if jax.device_count() % 2 == 0:
    tp_engine = ds.init_inference(lm, params, {"dtype": "float32",
                                               "tensor_parallel": 2})
    tp_out = np.asarray(tp_engine.generate(prompt, max_new_tokens=8,
                                           greedy=True))
    print(f"TP=2 continuation shape {tp_out.shape}")
else:
    print(f"skipping TP=2 (device count {jax.device_count()} is odd)")

# 2b. int8 weight-only quantized serving (single shard: WOQ+TP pending) ---
q_engine = ds.init_inference(lm, params, {
    "dtype": "float32", "quantize": True, "quant_bits": 8})
q_out = np.asarray(q_engine.generate(prompt, max_new_tokens=8, greedy=True))
print(f"int8 WOQ continuation shape {q_out.shape}")

# 3. feature tower: forward() is the product ------------------------------
tower_cfg = TransformerConfig(
    vocab_size=256, n_layer=2, n_head=4, d_model=64, max_seq=64,
    objective="feature", tie_embeddings=False, activation="quick_gelu")
tower = build_model(tower_cfg)
t_engine = ds.init_inference(tower, tower.init(jax.random.key(1)),
                             {"dtype": "float32"})
feats = np.asarray(t_engine.forward(prompt))
print(f"feature tower hidden states {feats.shape}")

# 4. MoE serving: expert dispatch inside the KV-cache decode scan ---------
# (reference DeepSpeedMoEInference; decode uses a single-group no-drop
# dispatch — models/moe.py _mlp_block_infer — and the router stays fp32
# through the engine's compute cast; expert banks WOQ-quantize like any
# other weight)
from deepspeed_tpu.models import mixtral

moe_cfg = mixtral("tiny", n_layer=2, n_head=4, n_kv_head=2, d_model=64,
                  d_ff=128, num_experts=4, vocab_size=256, max_seq=64,
                  moe_drop_tokens=False)
moe = build_model(moe_cfg)
moe_engine = ds.init_inference(moe, moe.init(jax.random.key(2)),
                               {"dtype": "float32", "quantize": True})
moe_out = np.asarray(moe_engine.generate(prompt, max_new_tokens=8,
                                         greedy=True))
print(f"MoE (4 experts, top-2, int8 banks) continuation shape {moe_out.shape}")
