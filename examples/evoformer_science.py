"""DS4Science evoformer attention: streamed pair bias with gradients.

The reference ships ~15 kLoC of CUTLASS for exactly this operation
(``csrc/deepspeed4science/evoformer_attn/``, the DS4Science release):
AlphaFold-style attention whose scores take an additive PAIR bias and
whose output is sigmoid-gated — memory-efficient even though the bias is
(B, H, S, S) and must receive gradients (the pair representation trains
through it). Here the whole thing is the Pallas flash kernel's bias
operand (`ops/flash_attention.py`): bias tiles stream through VMEM in the
forward and both backwards, dbias comes back as ds tiles, and the (B, H,
S, S) score/prob tensors never exist in HBM.

This example trains a toy MSA-row-attention block: per-head linear maps
produce the pair bias from a learned pair representation, attention runs
gated, and the loss gradient must flow back into BOTH the sequence
activations and the pair representation — the signature the CUTLASS
kernels exist to provide.

Run: DSTPU_EXAMPLE_SMOKE=1 python examples/evoformer_science.py
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.evoformer import evoformer_attention

SMOKE = os.environ.get("DSTPU_EXAMPLE_SMOKE") == "1"
B, S, H, hd = (2, 32, 4, 16) if SMOKE else (4, 256, 8, 32)
D_PAIR = 8

rng = np.random.default_rng(0)
x = jnp.asarray(rng.standard_normal((B, S, H * hd)), jnp.float32)
pair = jnp.asarray(rng.standard_normal((B, S, S, D_PAIR)), jnp.float32)
target = jnp.asarray(rng.standard_normal((B, S, H * hd)), jnp.float32)

params = {
    "wq": jnp.asarray(rng.standard_normal((H * hd, H * hd)) * 0.05),
    "wk": jnp.asarray(rng.standard_normal((H * hd, H * hd)) * 0.05),
    "wv": jnp.asarray(rng.standard_normal((H * hd, H * hd)) * 0.05),
    "w_gate": jnp.asarray(rng.standard_normal((H * hd, H * hd)) * 0.05),
    "w_bias": jnp.asarray(rng.standard_normal((D_PAIR, H)) * 0.05),
    "pair": pair,           # the pair representation itself is trainable
}


def block(p, x):
    q = (x @ p["wq"]).reshape(B, S, H, hd)
    k = (x @ p["wk"]).reshape(B, S, H, hd)
    v = (x @ p["wv"]).reshape(B, S, H, hd)
    gate = (x @ p["w_gate"]).reshape(B, S, H, hd)
    # (B, S, S, D_PAIR) @ (D_PAIR, H) -> (B, H, S, S) full-shape bias:
    # differentiable through the kernel's dbias tiles
    bias = jnp.einsum("bstd,dh->bhst", p["pair"], p["w_bias"])
    out = evoformer_attention(q, k, v, bias=bias, gate=gate)
    return out.reshape(B, S, H * hd)


def loss(p):
    return jnp.mean((block(p, x) - target) ** 2)


grad_fn = jax.jit(jax.value_and_grad(loss))
lr = 0.05
losses = []
for step in range(6 if SMOKE else 50):
    val, g = grad_fn(params)
    params = jax.tree.map(lambda w, gw: w - lr * gw, params, g)
    losses.append(float(val))
    if step % (2 if SMOKE else 10) == 0:
        gp = float(jnp.linalg.norm(g["pair"]))
        gb = float(jnp.linalg.norm(g["w_bias"]))
        print(f"step {step}: loss {val:.4f} |dpair| {gp:.2e} "
              f"|dw_bias| {gb:.2e}", flush=True)

assert losses[-1] < losses[0], losses
final_g = grad_fn(params)[1]
assert float(jnp.linalg.norm(final_g["pair"])) > 0, \
    "pair representation received no gradient"
print(f"evoformer block trained: {losses[0]:.4f} -> {losses[-1]:.4f} "
      "(pair-bias gradients flow through the streamed kernel)")
