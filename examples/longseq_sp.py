"""Long-context training with sequence parallelism: Ulysses and ring.

The reference's long-context story is DeepSpeed-Ulysses
(``blogs/deepspeed-ulysses``): shard the SEQUENCE over devices and
all-to-all q/k/v around attention so each device computes full-sequence
attention for a slice of heads. Here the same capability is two
attention_fn factories over a ``seq`` mesh axis:

- ``make_ulysses_attention`` — the a2a head/sequence swap (best on fast
  ICI, needs n_head % seq_parallel == 0),
- ``make_ring_attention`` — ppermute ring with online softmax (context
  parallelism: sequence never gathered anywhere, memory O(S/P); the
  reference has no equivalent kernel).

Run: DSTPU_EXAMPLE_SMOKE=1 JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/longseq_sp.py
(on a TPU pod slice, run unmodified — the mesh sizes to the real chips)
"""

import os

import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model, gpt2, tiny_test
from deepspeed_tpu.platform.mesh import MeshSpec, build_mesh
from deepspeed_tpu.runtime.dataloader import DataLoader, random_token_dataset
from deepspeed_tpu.sequence import (make_ring_attention,
                                    make_ulysses_attention)

smoke = os.environ.get("DSTPU_EXAMPLE_SMOKE") == "1"

if smoke:
    cfg, seq, micro, steps = tiny_test(n_layer=2, max_seq=128), 128, 2, 2
else:
    cfg, seq, micro, steps = gpt2("350m", max_seq=16384), 16384, 1, 50

# data x seq mesh: sequence over 4 devices, `data=-1` absorbs the rest —
# the same script runs on any slice whose device count divides by 4
mesh = build_mesh(MeshSpec(data=-1, seq=4))
dp = mesh.shape["data"]

# Long-context ALiBi (Bloom-style) rides the same ring: the distance
# bias is rebuilt from the ring's global per-step positions, so no
# O(S^2) bias tensor ever exists — position generalization at ring-scale
# context for free.
alibi_cfg = (tiny_test(n_layer=2, max_seq=128, pos_embedding="alibi")
             if smoke else gpt2("350m", max_seq=16384,
                                pos_embedding="alibi"))

for name, model_cfg, factory in (("ulysses", cfg, make_ulysses_attention),
                                 ("ring", cfg, make_ring_attention),
                                 ("alibi-ring", alibi_cfg,
                                  make_ring_attention)):
    model = build_model(model_cfg, attention_fn=factory(mesh))
    engine = ds.initialize({
        "train_batch_size": micro * dp,
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 1},
        "remat": {"enabled": True, "policy": "dots_saveable"},
    }, model, mesh=mesh)

    data = random_token_dataset(engine.train_batch_size * steps, seq_len=seq,
                                vocab_size=model_cfg.vocab_size,
                                learnable=smoke)
    loader = DataLoader(data, local_batch_size=engine.train_batch_size,
                        shuffle=False)
    losses = [float(engine.train_batch(batch)["loss"]) for batch in loader]
    assert all(np.isfinite(losses)), (name, losses)
    print(f"{name}: seq={seq} sharded over {mesh.shape['seq']} devices, "
          f"losses {losses[0]:.4f} -> {losses[-1]:.4f}")

print("longseq_sp example done")
