"""Telemetry-plane bench + tier-1 gate (observability/server.py).

``--smoke`` is the CPU tier-1 gate (wired via
tests/unit/test_telemetry.py, same pattern as bench_serving.py):

1. **zero-cost when off / zero-programs when on** — the same workload
   runs on an engine with telemetry+goodput off and one with them on;
   the compiled-program counts must be IDENTICAL (the telemetry plane
   adds threads and clock reads, never programs — the serving
   compile-freeze discipline extended to the ops surface);
2. **scrapeable** — ``GET /metrics`` over the ephemeral-port server
   parses with the existing exposition reader and carries the
   ``Serve/*`` + goodput gauges;
3. **byte-compatible** — the ``/metrics`` body equals the textfile the
   Prometheus sink writes for the same registry events (shared
   ``expfmt`` renderer, pinned end to end);
4. **goodput sums** — productive + badput buckets == wall time within
   1% on the real-clock run, with the compile window attributed via the
   engine's compile counter (badput_compile > 0 on a cold engine).

Prints one JSON line ending in "smoke-pass"; exits nonzero on failure.
Without ``--smoke``: measures scrape latency under live traffic and
writes TELEMETRY_BENCH.json.
"""

import json
import sys
import time
import urllib.request

import numpy as np

from bench_serving import build, make_workload, run_continuous


def _get(port, path, timeout=5.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.status, r.read().decode("utf-8")


# ------------------------------------------------------------------ smoke
def smoke():
    from deepspeed_tpu.observability.expfmt import parse_prometheus_textfile
    from deepspeed_tpu.observability.sinks import PrometheusTextfileSink

    slots, max_len, chunk = 4, 64, 16
    reqs = make_workload(24, seed=3)

    # (1a) baseline: telemetry and goodput OFF — count compiled programs
    _, _, _, srv_off = build(slots, max_len, chunk)
    run_continuous(srv_off, reqs)
    compiles_off = srv_off.compiles

    # (1b) same workload, telemetry + goodput ON
    _, _, _, srv = build(slots, max_len, chunk, goodput=True,
                         telemetry={"enabled": True, "port": 0})
    port = srv.telemetry.port
    assert port > 0, "ephemeral bind failed"
    run_continuous(srv, reqs)
    assert srv.compiles == compiles_off, (
        f"telemetry/goodput changed the program set: {srv.compiles} "
        f"programs vs {compiles_off} with them off")

    # (2) live scrape parses and carries the expected series
    status, body = _get(port, "/metrics")
    assert status == 200, f"/metrics -> {status}"
    vals = parse_prometheus_textfile(body)
    assert vals, "scrape parsed to nothing"
    for need in ("dstpu_serve_retired", "dstpu_serve_goodput_frac",
                 "dstpu_serve_ready"):
        assert need in vals, f"{need} missing from /metrics ({len(vals)})"
    assert vals["dstpu_serve_retired"] == len(reqs)

    # (3) byte-compat: the sink's textfile for the same registry events
    # must equal the /metrics body (shared expfmt renderer)
    import tempfile
    from pathlib import Path

    status, body2 = _get(port, "/metrics")
    reg = srv.stats.registry
    step = int(reg.counter("Serve/iterations").value)
    with tempfile.TemporaryDirectory() as td:
        sink = PrometheusTextfileSink({"output_path": td,
                                       "job_name": "smoke"})
        sink.write_events(reg.to_events(step))
        sink.flush()
        file_text = (Path(td) / "smoke.prom").read_text()
    assert file_text == body2, (
        "textfile sink and /metrics drifted for the same registry "
        "snapshot")

    # (4) goodput decomposition sums to wall within 1%; the cold
    # engine's compile window landed in badput_compile
    status, gtext = _get(port, "/goodput")
    assert status == 200, f"/goodput -> {status}"
    g = json.loads(gtext)
    total = g["productive_s"] + g["badput_total_s"]
    assert abs(total - g["wall_s"]) <= 0.01 * max(g["wall_s"], 1e-9), (
        f"goodput buckets sum to {total}, wall is {g['wall_s']}")
    assert g["badput_s"]["compile"] > 0, (
        "cold engine shows no compile badput — compile-counter "
        "attribution broke")
    assert g["productive_s"] > 0

    # probes answer with the k8s contract
    assert _get(port, "/healthz")[0] == 200
    assert _get(port, "/readyz")[0] == 200

    srv.close()
    print(json.dumps({
        "smoke": True, "requests": len(reqs),
        "compiled_programs": compiles_off,
        "goodput_frac": round(g["goodput_frac"], 4),
        "badput_compile_s": round(g["badput_s"]["compile"], 4),
        "metrics_series": len(vals),
        "byte_compatible": True,
        "verdict": "smoke-pass",
    }))


# ------------------------------------------------------------------- bench
def bench(n=32, scrapes=50):
    """Scrape latency + overhead picture under live traffic."""
    slots, max_len, chunk = 4, 64, 16
    reqs = make_workload(n, seed=5)
    _, _, _, srv = build(slots, max_len, chunk, goodput=True,
                         telemetry={"enabled": True, "port": 0})
    port = srv.telemetry.port
    run_continuous(srv, reqs)          # warm: compiles out of the way
    lat = []
    for _ in range(scrapes):
        t0 = time.perf_counter()
        status, body = _get(port, "/metrics")
        lat.append(time.perf_counter() - t0)
        assert status == 200
    _, gtext = _get(port, "/goodput")
    g = json.loads(gtext)
    srv.close()
    lat.sort()
    return {
        "scrapes": scrapes,
        "scrape_p50_ms": round(1e3 * lat[len(lat) // 2], 3),
        "scrape_p99_ms": round(1e3 * lat[int(len(lat) * 0.99) - 1], 3),
        "metrics_bytes": len(body),
        "goodput": {k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in g.items() if not isinstance(v, dict)},
        "badput_s": {k: round(v, 6) for k, v in g["badput_s"].items()},
    }


def main():
    res = bench()
    import os

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "TELEMETRY_BENCH.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        smoke()
    else:
        main()
