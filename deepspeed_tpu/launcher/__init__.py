from .hostfile import filter_resources, parse_hostfile, parse_inclusion_exclusion
from .runner import main

__all__ = ["parse_hostfile", "filter_resources", "parse_inclusion_exclusion",
           "main"]
