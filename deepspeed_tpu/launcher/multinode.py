"""Multinode runner command builders: SLURM / OpenMPI / MPICH / Intel MPI.

Analog of the reference's ``launcher/multinode_runner.py:18-366`` (PDSH,
OpenMPI, MPICH, IMPI, SLURM, MVAPICH command builders). ssh/pdsh live in
``runner.py``; these cover scheduler-managed sites (GKE/SLURM clusters
fronting TPU pods, CPU fleets). Each builder returns ONE argv that starts
the per-node launcher (``launcher.launch``) on every allocated node — the
runner does not need MPI for communication (JAX's coordination service does
rendezvous); MPI/SLURM is only the process *starter*.

Because scheduler starters run the SAME command on every node (node identity
comes from the starter's env: SLURM_NODEID / OMPI_COMM_WORLD_RANK /
PMI_RANK), they require a homogeneous allocation — per-host slot counts
must match and per-host slot *filters* can't be expressed. Both are
validated loudly; heterogeneous or slot-filtered jobs belong on the
ssh/pdsh path. Environment exports are inlined into the remote command
(``export K=V;`` with shell quoting) — srun's ``--export K=V`` list splits
on commas and silently truncates values like LIBTPU_INIT_ARGS.
"""

from __future__ import annotations

import shlex
from collections import OrderedDict

# Placeholders substituted per starter; they are the ONLY unquoted shell
# expansions in the remote command.
_NODE_RANK = "__DSTPU_NODE_RANK__"
_PROC_BASE = "__DSTPU_PROC_BASE__"


def check_homogeneous(resources: "OrderedDict[str, list[int]]",
                      launcher: str) -> int:
    """Scheduler starters can't express per-host differences; fail loudly
    (the silent alternative is a hung rendezvous). Returns the per-node
    slot count."""
    counts = {h: len(s) for h, s in resources.items()}
    if len(set(counts.values())) > 1:
        raise SystemExit(
            f"dstpu: --launcher {launcher} runs one identical command per "
            f"node and needs homogeneous slot counts, got {counts}; use "
            "--launcher ssh/pdsh for heterogeneous hosts")
    per_node = next(iter(counts.values()))
    for host, slots in resources.items():
        if slots != list(range(per_node)):
            raise SystemExit(
                f"dstpu: --launcher {launcher} cannot forward per-host slot "
                f"filters (host {host} selected {slots}); use ssh/pdsh")
    return per_node


def _remote_command(args, launch_argv_fn, nnodes: int, nproc: int,
                    exports: "OrderedDict[str, str]",
                    coordinator: str) -> str:
    """The bash -c payload: inlined exports + the shared launch argv (from
    ``runner._launch_cmd`` via ``launch_argv_fn`` — one construction site,
    no drift) with rank placeholders left as shell expansions."""
    argv = launch_argv_fn(args, _NODE_RANK, nnodes, nproc,
                          nnodes * nproc, _PROC_BASE, coordinator)
    quoted = []
    for part in argv:
        if part in (_NODE_RANK, _PROC_BASE):
            quoted.append(part)   # substituted below, must stay expandable
        else:
            quoted.append(shlex.quote(part))
    cmd = " ".join(quoted)
    export_str = "".join(f"export {k}={shlex.quote(v)}; "
                         for k, v in exports.items())
    return export_str + cmd


def _finish(cmd: str, node_rank_var: str, nproc: int) -> str:
    return (cmd.replace(_NODE_RANK, f'"${node_rank_var}"')
               .replace(_PROC_BASE, f'"$(({node_rank_var} * {nproc}))"'))


def slurm_command(args, resources, coordinator, exports,
                  launch_argv_fn) -> list[str]:
    """``srun`` line (reference ``SlurmRunner.get_cmd``,
    ``multinode_runner.py:283``): one task per node, rank from SLURM_NODEID."""
    nproc = check_homogeneous(resources, "slurm")
    nnodes = len(resources)
    inner = _finish(_remote_command(args, launch_argv_fn, nnodes, nproc,
                                    exports, coordinator),
                    "SLURM_NODEID", nproc)
    cmd = ["srun", "--nodes", str(nnodes), "--ntasks", str(nnodes),
           "--ntasks-per-node", "1"]
    if getattr(args, "slurm_partition", None):
        cmd += ["--partition", args.slurm_partition]
    cmd += ["bash", "-c", inner]
    return cmd


def openmpi_command(args, resources, coordinator, exports,
                    launch_argv_fn) -> list[str]:
    """``mpirun`` line (reference ``OpenMPIRunner.get_cmd``,
    ``multinode_runner.py:108``): one rank per node, rank from
    OMPI_COMM_WORLD_RANK."""
    nproc = check_homogeneous(resources, "openmpi")
    nnodes = len(resources)
    hosts = ",".join(f"{h}:1" for h in resources)
    inner = _finish(_remote_command(args, launch_argv_fn, nnodes, nproc,
                                    exports, coordinator),
                    "OMPI_COMM_WORLD_RANK", nproc)
    return ["mpirun", "-n", str(nnodes), "--host", hosts,
            "--allow-run-as-root", "--tag-output", "bash", "-c", inner]


def mpich_command(args, resources, coordinator, exports,
                  launch_argv_fn) -> list[str]:
    """``mpiexec`` line (reference ``MPICHRunner`` / ``IMPIRunner``,
    ``multinode_runner.py:159,197``): rank from PMI_RANK."""
    nproc = check_homogeneous(resources, "mpich")
    nnodes = len(resources)
    hosts = ",".join(resources)
    inner = _finish(_remote_command(args, launch_argv_fn, nnodes, nproc,
                                    exports, coordinator),
                    "PMI_RANK", nproc)
    return ["mpiexec", "-n", str(nnodes), "-hosts", hosts, "-ppn", "1",
            "bash", "-c", inner]


BUILDERS = {
    "slurm": slurm_command,
    "openmpi": openmpi_command,
    "mpich": mpich_command,
    "impi": mpich_command,   # Intel MPI shares the mpiexec/PMI contract
}
