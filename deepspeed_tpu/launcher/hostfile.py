"""Hostfile parsing + include/exclude filtering.

Analog of the reference launcher's resource-pool handling
(``launcher/runner.py:201`` ``fetch_hostfile`` and ``:256``
``parse_inclusion_exclusion``): a hostfile lists one host per line as
``hostname slots=N``; ``--include``/``--exclude`` filters select hosts and
per-host slots with the syntax ``host1@host2:0,2`` (``@`` separates hosts,
``:`` introduces a slot list).
"""

from __future__ import annotations

import re
from collections import OrderedDict

_LINE = re.compile(r"^(?P<host>\S+)(\s+slots=(?P<slots>\d+))?\s*(#.*)?$")


def parse_hostfile(text: str) -> "OrderedDict[str, int]":
    """Hostfile text → ordered {hostname: slot_count}. Blank lines and
    ``#`` comments are skipped; a missing ``slots=`` means 1."""
    pool: "OrderedDict[str, int]" = OrderedDict()
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if not m:
            raise ValueError(f"hostfile line {lineno} unparsable: {raw!r}")
        host = m.group("host")
        if host in pool:
            raise ValueError(f"hostfile line {lineno}: duplicate host {host!r}")
        pool[host] = int(m.group("slots") or 1)
    if not pool:
        raise ValueError("hostfile contains no hosts")
    return pool


def _parse_filter(spec: str) -> "OrderedDict[str, list[int] | None]":
    """``host1@host2:0,2`` → {host1: None, host2: [0, 2]} (None = all slots)."""
    out: "OrderedDict[str, list[int] | None]" = OrderedDict()
    for part in filter(None, spec.split("@")):
        if ":" in part:
            host, slots = part.split(":", 1)
            out[host] = sorted(int(s) for s in slots.split(",") if s != "")
        else:
            out[part] = None
    return out


def parse_inclusion_exclusion(pool: "OrderedDict[str, int]",
                              include: str = "",
                              exclude: str = "") -> "OrderedDict[str, list[int]]":
    """Apply include/exclude specs to a {host: slots} pool, returning
    ordered {host: [slot ids]}. ``include`` and ``exclude`` are mutually
    exclusive (reference behavior)."""
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")
    active: "OrderedDict[str, list[int]]" = OrderedDict(
        (h, list(range(n))) for h, n in pool.items())
    if include:
        spec = _parse_filter(include)
        unknown = [h for h in spec if h not in active]
        if unknown:
            raise ValueError(f"--include names unknown hosts: {unknown}")
        picked: "OrderedDict[str, list[int]]" = OrderedDict()
        for host, slots in spec.items():
            avail = active[host]
            if slots is None:
                picked[host] = avail
            else:
                bad = [s for s in slots if s not in avail]
                if bad:
                    raise ValueError(f"--include slot(s) {bad} not in {host} "
                                     f"(has {len(avail)})")
                picked[host] = slots
        return picked
    if exclude:
        spec = _parse_filter(exclude)
        unknown = [h for h in spec if h not in active]
        if unknown:
            raise ValueError(f"--exclude names unknown hosts: {unknown}")
        for host, slots in spec.items():
            if slots is None:
                del active[host]
            else:
                active[host] = [s for s in active[host] if s not in slots]
                if not active[host]:
                    del active[host]
    return active


def filter_resources(pool: "OrderedDict[str, int]", include: str = "",
                     exclude: str = "", num_nodes: int = -1,
                     num_procs: int = -1) -> "OrderedDict[str, list[int]]":
    """Full resource resolution: filters, then ``--num_nodes`` /
    ``--num_procs`` truncation (reference ``parse_resource_filter``)."""
    res = parse_inclusion_exclusion(pool, include, exclude)
    if num_nodes > 0:
        if num_nodes > len(res):
            raise ValueError(f"--num_nodes={num_nodes} but only {len(res)} "
                             "hosts available after filtering")
        res = OrderedDict(list(res.items())[:num_nodes])
    if num_procs > 0:
        res = OrderedDict((h, s[:num_procs]) for h, s in res.items())
    return res
