"""Per-node process launcher.

Analog of the reference's ``launcher/launch.py:132-251``: spawn one OS
process per local rank with the distributed env set, redirect logs, poll
children, and kill the whole local group if any child dies (the
``sigkill_handler``).  On TPU pods the common shape is ONE process per host
owning all local chips (JAX convention), so ``--nproc`` defaults to 1; the
multi-process-per-host mode exists for CPU simulation, subdevice tunnels,
and the multi-process test harness (SURVEY §4's DistributedTest analog).

Env contract consumed by ``platform.accelerator.init_distributed``:
  DSTPU_COORDINATOR     coordinator address host:port (process 0's host)
  DSTPU_NUM_PROCESSES   global process count
  DSTPU_PROCESS_ID      this process's global id
  DSTPU_LOCAL_RANK      local rank on this node
  DSTPU_NODE_RANK       this node's rank
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def build_child_env(base: dict, *, coordinator: str, num_processes: int,
                    process_id: int, local_rank: int, node_rank: int,
                    slots: "list[int] | None" = None) -> dict:
    env = dict(base)
    env.update({
        "DSTPU_COORDINATOR": coordinator,
        "DSTPU_NUM_PROCESSES": str(num_processes),
        "DSTPU_PROCESS_ID": str(process_id),
        "DSTPU_LOCAL_RANK": str(local_rank),
        "DSTPU_NODE_RANK": str(node_rank),
    })
    if slots is not None:
        # Selected device slots (hostfile :slot filters). launch_local
        # enforces len(slots) == nproc, so each child owns exactly ONE
        # selected chip: pin it via libtpu's env BEFORE the interpreter
        # starts — the TPU analog of the reference exporting
        # CUDA_VISIBLE_DEVICES per rank (launcher/launch.py:221). Explicit
        # user pinning in the parent env wins.
        env["DSTPU_VISIBLE_SLOTS"] = ",".join(str(s) for s in slots)
        env["DSTPU_SLOT_ID"] = str(slots[local_rank])
        if not base.get("TPU_VISIBLE_CHIPS") and not base.get("TPU_VISIBLE_DEVICES"):
            env["TPU_VISIBLE_CHIPS"] = str(slots[local_rank])
            env.setdefault("TPU_CHIPS_PER_PROCESS_BOUNDS", "1,1,1")
    return env


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="dstpu-launch",
                                description="per-node process launcher")
    p.add_argument("--num_processes", type=int, default=None,
                   help="GLOBAL process count (hosts may have uneven slots); "
                        "default nnodes*nproc")
    p.add_argument("--proc_id_base", type=int, default=None,
                   help="global id of this node's first process; "
                        "default node_rank*nproc")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nproc", type=int, default=1,
                   help="processes on this node (JAX convention: 1/host)")
    p.add_argument("--coordinator", default="127.0.0.1:12321",
                   help="host:port of process 0's coordination service")
    p.add_argument("--slots", default=None,
                   help="comma list of device-slot ids selected for this "
                        "node (from hostfile include/exclude filters); "
                        "child i gets DSTPU_SLOT_ID=slots[i]")
    p.add_argument("--log_dir", default=None,
                   help="write per-rank logs here instead of inheriting stdio")
    p.add_argument("--module", action="store_true",
                   help="run script as a python module (python -m)")
    p.add_argument("script", help="training script to launch")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch_local(args) -> int:
    """Spawn ``nproc`` children, babysit them, return the first failure code."""
    num_processes = (args.num_processes if args.num_processes is not None
                     else args.nnodes * args.nproc)
    proc_id_base = (args.proc_id_base if args.proc_id_base is not None
                    else args.node_rank * args.nproc)
    cmd = [sys.executable]
    if args.module:
        cmd.append("-m")
    cmd.append(args.script)
    cmd += args.script_args

    children: list[subprocess.Popen] = []
    logs = []
    slots = ([int(s) for s in args.slots.split(",")]
             if getattr(args, "slots", None) else None)
    if slots is not None and len(slots) != args.nproc:
        raise SystemExit(
            f"dstpu-launch: {args.nproc} processes but {len(slots)} selected "
            f"slots ({slots}); refusing to oversubscribe/underuse device "
            "slots — adjust --nproc or the hostfile include/exclude filters")
    for local_rank in range(args.nproc):
        process_id = proc_id_base + local_rank
        env = build_child_env(os.environ, coordinator=args.coordinator,
                              num_processes=num_processes,
                              process_id=process_id, local_rank=local_rank,
                              node_rank=args.node_rank,
                              slots=slots)
        stdout = stderr = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            f = open(os.path.join(args.log_dir, f"rank_{process_id}.log"), "w")
            logs.append(f)
            stdout, stderr = f, subprocess.STDOUT
        children.append(subprocess.Popen(cmd, env=env, stdout=stdout,
                                         stderr=stderr))

    def _kill_all(signum=None, frame=None):
        for c in children:
            if c.poll() is None:
                c.terminate()
        deadline = time.time() + 10
        for c in children:
            try:
                c.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                c.kill()

    signal.signal(signal.SIGTERM, _kill_all)
    signal.signal(signal.SIGINT, _kill_all)

    rc = 0
    try:
        # Poll loop (reference launch.py polls children and sigkills the
        # group on any nonzero exit so no rank hangs on a dead collective).
        live = set(range(len(children)))
        while live:
            time.sleep(0.3)
            for i in sorted(live):
                code = children[i].poll()
                if code is None:
                    continue
                live.discard(i)
                if code != 0:
                    rc = rc or code
                    print(f"[dstpu-launch] rank {i} exited rc={code}; "
                          "terminating local group", file=sys.stderr, flush=True)
                    _kill_all()
                    live.clear()
                    break
    finally:
        for f in logs:
            f.close()
    return rc


def main(argv=None) -> None:
    sys.exit(launch_local(parse_args(argv)))


if __name__ == "__main__":
    main()
