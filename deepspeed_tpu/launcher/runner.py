"""``dstpu`` CLI: the multi-node entry point.

Analog of the reference launcher (``launcher/runner.py:389`` / ``bin/deepspeed``):
parse a hostfile, apply ``--include``/``--exclude`` filters, propagate the
environment, and start one per-node launcher on every host.  On a single
host this execs ``launcher.launch`` directly; across hosts it builds ssh (or
pdsh) command lines — the TPU-pod equivalent of the reference's PDSH/MPI
multinode runners (``launcher/multinode_runner.py:18-366``).

Differences from the reference that are deliberate TPU choices:
- One process per host by default (JAX owns all local chips per process);
  ``--nproc`` overrides for CPU-simulation and tests.
- No MPI dependency: process coordination is JAX's builtin distributed
  service (process 0 is the coordinator), so the launcher only has to get
  processes *started* with the right env.
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
from collections import OrderedDict

from .hostfile import filter_resources, parse_hostfile

# Env prefixes forwarded to remote nodes (reference propagates a curated
# .deepspeed_env list; we forward the framework/runtime-relevant prefixes).
_FORWARD_PREFIXES = ("DSTPU_", "JAX_", "XLA_", "LIBTPU_", "TPU_", "PYTHON")
_ENV_FILE = ".dstpu_env"


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="dstpu", description="deepspeed_tpu multi-host launcher")
    p.add_argument("-H", "--hostfile", default=None,
                   help="path to a 'host slots=N' hostfile; absent = localhost")
    p.add_argument("-i", "--include", default="",
                   help="host/slot filter, e.g. 'node1@node2:0,1'")
    p.add_argument("-e", "--exclude", default="",
                   help="host/slot filter to drop")
    p.add_argument("--num_nodes", type=int, default=-1,
                   help="use only the first N filtered hosts")
    p.add_argument("--nproc", type=int, default=0,
                   help="processes per node; 0 (default) = one per hostfile "
                        "slot, or 1 on a bare localhost (JAX owns all chips)")
    p.add_argument("--master_addr", default=None,
                   help="coordinator host (default: first host / 127.0.0.1)")
    p.add_argument("--master_port", type=int, default=12321)
    p.add_argument("--ssh_port", type=int, default=22)
    p.add_argument("--launcher",
                   choices=("ssh", "pdsh", "slurm", "openmpi", "mpich", "impi"),
                   default="ssh")
    p.add_argument("--slurm_partition", default=None)
    p.add_argument("--env_file", default=_ENV_FILE,
                   help="extra KEY=VALUE lines to export on every node")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--module", action="store_true",
                   help="run the script as a python module")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def gather_env(env_file: str | None) -> "OrderedDict[str, str]":
    """Environment to propagate: matching prefixes + env-file overrides."""
    out: "OrderedDict[str, str]" = OrderedDict()
    for k, v in os.environ.items():
        if k.startswith(_FORWARD_PREFIXES) and k != "PYTHONPATH":
            out[k] = v
    if env_file and os.path.isfile(env_file):
        with open(env_file) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                out[k.strip()] = v.strip()
    return out


def node_proc_counts(args, resources: "OrderedDict[str, list[int]]") -> list[int]:
    """Per-node process counts: hostfile slots by default, ``--nproc``
    overrides uniformly (hosts may be heterogeneous)."""
    return [args.nproc if args.nproc > 0 else len(slots)
            for slots in resources.values()]


def _launch_cmd(args, node_rank: int, nnodes: int, nproc: int,
                num_processes: int, proc_id_base: int, coordinator: str,
                slots: "list[int] | None" = None) -> list[str]:
    cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
           "--nnodes", str(nnodes), "--node_rank", str(node_rank),
           "--nproc", str(nproc), "--num_processes", str(num_processes),
           "--proc_id_base", str(proc_id_base), "--coordinator", coordinator]
    if slots is not None:
        cmd += ["--slots", ",".join(str(s) for s in slots)]
    if args.log_dir:
        cmd += ["--log_dir", args.log_dir]
    if args.module:
        cmd += ["--module"]
    cmd.append(args.script)
    cmd += args.script_args
    return cmd


def build_remote_commands(args, resources: "OrderedDict[str, list[int]]",
                          coordinator: str) -> "OrderedDict[str, list[str]]":
    """Per-host shell commands for the multi-node case (unit-testable;
    reference ``multinode_runner.py`` command builders)."""
    exports = gather_env(args.env_file)
    export_str = " ".join(f"export {k}={shlex.quote(v)};" for k, v in exports.items())
    cwd = os.path.abspath(os.getcwd())
    counts = node_proc_counts(args, resources)
    total = sum(counts)
    cmds: "OrderedDict[str, list[str]]" = OrderedDict()
    base = 0
    for node_rank, host in enumerate(resources):
        inner = _launch_cmd(args, node_rank, len(resources), counts[node_rank],
                            total, base, coordinator,
                            slots=resources[host])
        base += counts[node_rank]
        remote = f"{export_str} cd {shlex.quote(cwd)}; " + \
                 " ".join(shlex.quote(c) for c in inner)
        if args.launcher == "pdsh":
            cmds[host] = ["pdsh", "-S", "-w", host, remote]
        else:
            cmds[host] = ["ssh", "-o", "StrictHostKeyChecking=no",
                          "-p", str(args.ssh_port), host, remote]
    return cmds


def main(argv=None) -> None:
    args = parse_args(argv)

    if args.hostfile:
        with open(args.hostfile) as f:
            pool = parse_hostfile(f.read())
    else:
        pool = OrderedDict([("localhost", args.nproc if args.nproc > 0 else 1)])
    resources = filter_resources(pool, args.include, args.exclude,
                                 num_nodes=args.num_nodes)
    if not resources:
        raise SystemExit("dstpu: no hosts left after filtering")

    first_host = next(iter(resources))
    master = args.master_addr or (
        "127.0.0.1" if first_host == "localhost" else first_host)
    coordinator = f"{master}:{args.master_port}"

    if len(resources) == 1 and first_host in ("localhost", "127.0.0.1"):
        # Single node: run the per-node launcher in-process.
        from . import launch as launch_mod

        nproc = node_proc_counts(args, resources)[0]
        largs = launch_mod.parse_args(
            ["--nnodes", "1", "--node_rank", "0", "--nproc", str(nproc),
             "--coordinator", coordinator]
            + (["--log_dir", args.log_dir] if args.log_dir else [])
            + (["--module"] if args.module else [])
            + [args.script] + args.script_args)
        sys.exit(launch_mod.launch_local(largs))

    if args.launcher in ("slurm", "openmpi", "mpich", "impi"):
        # Scheduler-managed starters run ONE command that fans out to every
        # node (reference SlurmRunner/OpenMPIRunner/MPICHRunner); node rank
        # comes from the starter's env, so there is no per-host Popen table.
        # --nproc overrides slot counts exactly as on the ssh path.
        from .multinode import BUILDERS

        if args.nproc > 0:
            resources = OrderedDict(
                (h, list(range(args.nproc))) for h in resources)
        cmd = BUILDERS[args.launcher](args, resources, coordinator,
                                      gather_env(args.env_file), _launch_cmd)
        sys.exit(subprocess.call(cmd))

    cmds = build_remote_commands(args, resources, coordinator)
    procs = {h: subprocess.Popen(c) for h, c in cmds.items()}
    rc = 0
    try:
        # Poll ALL nodes; the first failure terminates the survivors so no
        # node hangs in a dead rendezvous (reference sigkill_handler).
        import time as _time

        live = dict(procs)
        while live and rc == 0:
            _time.sleep(0.5)
            for host in list(live):
                code = live[host].poll()
                if code is None:
                    continue
                del live[host]
                if code != 0:
                    print(f"dstpu: node {host} exited rc={code}; "
                          "terminating remaining nodes", file=sys.stderr)
                    rc = code
        for proc in live.values():
            proc.terminate()
        for proc in live.values():
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
    except KeyboardInterrupt:
        for proc in procs.values():
            proc.terminate()
        rc = 130
    sys.exit(rc)


if __name__ == "__main__":
    main()
