"""Unified KV tiering engine: one pin/copy/verify/evict discipline
over ranked page stores (host DRAM above, NVMe below).

PR 14's :class:`~.hostkv.HostKVTier` built the demote-on-evict /
restore-on-resume loop for ONE rung (pinned host memory). This module
factors that loop's store discipline into a reusable base so the next
rung — disk, via the training side's AIO machinery — plugs in without a
second private copy (ZeRO-Infinity's streaming playbook, PAPERS.md):

- :class:`TierStore` — the ONE implementation of the store contract:
  ``(prefix_len, token_hash)`` keying (the ghost-list spelling), exact
  tail-token verification, CRC integrity with fallback-to-recompute,
  LRU byte budget with pin-aware pruning, pinned match→consume/release
  admission handshake, and the full ``Serve/<kind>_*`` metric family.
  Subclasses supply only the payload transport (where tile bytes live).
- :class:`~.hostkv.HostKVTier` — the DRAM rung: tiles stay in RAM on
  the entry (a thin subclass; its public surface is unchanged).
- :class:`NVMeKVTier` — the disk rung: tiles are serialized to one
  swap file per block through :class:`~..ops.aio.AIOFileStore` (async
  write-behind on put, synchronous verified read on match), so
  resumable-session residency is bounded by disk, not DRAM.
- :class:`TieringEngine` — the coordinator the :class:`~.pages.PagePool`
  talks to when more than one rung is configured: puts land in the top
  store and overflow SPILLS downward (host prune → NVMe put), matches
  probe rungs in rank order per block (host hit beats disk hit), and
  consumes stack mixed-rung blocks into one restore payload. It speaks
  the exact ``pool.host`` protocol, so the pool/engine plumbing is
  rung-count-agnostic.

Degrade-never-crash is uniform: a pruned, collision-shadowed, torn,
missing, or checksum-corrupt copy at ANY rung is simply not a match —
the block stays in the chunk plan and is recomputed, with the failure
counted in ``Serve/<kind>_fallbacks``.
"""

from __future__ import annotations

import os
import tempfile
import zlib
from collections import OrderedDict
from typing import Callable, List, Optional

import numpy as np

from ..observability.workload import prefix_hashes, token_hash

__all__ = ["TierStore", "NVMeKVTier", "TieringEngine", "tiles_crc"]


def tiles_crc(tiles: dict) -> int:
    """Integrity checksum over a page's raw host bytes: a corrupt or
    torn copy must degrade to recompute, never into the cache. Chained
    crc32 in sorted tile-name order — identical to the crc32 of the
    sorted-order byte concatenation, so the NVMe rung can verify its
    single flat file read against the same value."""
    h = 0
    for key in sorted(tiles):
        h = zlib.crc32(np.ascontiguousarray(tiles[key]).tobytes(), h)
    return h


class TierStore:
    """One rung of the KV hierarchy: a bounded LRU store of full-block
    page payloads keyed by ``(prefix_len, prefix_hash)``.

    All bookkeeping — budgets, pins, CRC contract, metrics, the
    match/consume/release admission handshake — lives here once.
    Subclasses implement only payload transport:

    - ``_attach(key, ent, tiles)`` — persist a page's tiles on ``ent``
      (RAM reference, or an async file write).
    - ``_verify(ent)`` — produce the tiles back, integrity-checked;
      ``None`` means corrupt/torn/missing (the caller counts and drops).
    - ``_unfetch(ent)`` — release any fetch-side staging when an
      admission defers (entry stays resident).
    - ``_discard(ent)`` — final payload cleanup when an entry leaves
      the store (consume/prune/corrupt-drop), keeping any already
      fetched tiles intact for the in-flight consumer.

    ``kind`` prefixes the metric family: ``Serve/<kind>_*``.
    """

    kind = "tier"

    def __init__(self, capacity_bytes: int, page_size: int,
                 registry=None, clock: Optional[Callable] = None):
        if capacity_bytes < 1:
            raise ValueError(f"{self.kind} capacity_bytes must be >= 1, "
                             f"got {capacity_bytes}")
        self.capacity_bytes = int(capacity_bytes)
        self.page_size = int(page_size)
        self.registry = registry
        self.clock = clock if clock is not None else (lambda: 0.0)
        self.entries: OrderedDict = OrderedDict()
        self.bytes_used = 0
        # per-owner resident bytes (observability/tenantscope.py): every
        # entry carries the tenant that first demoted its block, and the
        # five bookkeeping paths that move bytes_used (put, replace,
        # prune, corrupt-drop, consume-pop) move the owner's cell by the
        # SAME nbytes — so Σ owner_bytes == bytes_used exactly whenever
        # every put carried an owner, and owner attribution survives the
        # spill chain down to the NVMe rung.
        self.owner_bytes: dict = {}
        # the rung below (wired by TieringEngine): prune victims spill
        # there instead of vanishing
        self.spill_to: Optional["TierStore"] = None
        # cumulative accounting (the capacity advisor's achieved side)
        self.demotes = 0            # pages demoted into the tier
        self.demote_bytes = 0
        self.demote_skips = 0       # pages too large for the whole budget
        self.restores = 0           # restore OPERATIONS (one per admission)
        self.restored_pages = 0
        self.restored_tokens = 0
        self.restore_bytes = 0
        self.restore_wait_s = 0.0   # summed dispatch wall of all restores
        self.hits = 0               # blocks served from the tier
        self.misses = 0             # continuation probes that found nothing
        self.prunes = 0             # entries LRU-dropped for capacity
        self.pruned_bytes = 0
        self.spills = 0             # prune victims handed to the rung below
        self.fallbacks = 0          # corrupt/mismatched copies -> recompute
        self._publish()

    # ---------------------------------------------------- payload transport
    def _attach(self, key, ent: dict, tiles: dict) -> None:
        raise NotImplementedError

    def _verify(self, ent: dict):
        raise NotImplementedError

    def _unfetch(self, ent: dict) -> None:
        pass

    def _discard(self, ent: dict) -> None:
        pass

    # ------------------------------------------------------------- metrics
    def _publish(self) -> None:
        if self.registry is None:
            return
        self.registry.set_gauges({
            f"Serve/{self.kind}_pages": float(len(self.entries)),
            f"Serve/{self.kind}_bytes": float(self.bytes_used),
            f"Serve/{self.kind}_capacity_bytes": float(self.capacity_bytes),
            f"Serve/{self.kind}_occupancy": (
                self.bytes_used / self.capacity_bytes),
            f"Serve/{self.kind}_pressure": float(self.pressure),
        })

    def _count(self, name: str, n: int = 1) -> None:
        if self.registry is not None and n:
            self.registry.counter(name).inc(n)

    def _owner_delta(self, owner, nbytes: int) -> None:
        if owner is None:
            return
        b = self.owner_bytes.get(owner, 0) + int(nbytes)
        if b <= 0:
            self.owner_bytes.pop(owner, None)
        else:
            self.owner_bytes[owner] = b

    @property
    def pressure(self) -> bool:
        """True when the tier cannot fit another typical page without
        pruning a cold one — the next demotion starts losing history."""
        if not self.entries:
            return False
        mean = self.bytes_used / len(self.entries)
        return self.capacity_bytes - self.bytes_used < mean

    # ------------------------------------------------------------- demotion
    def put(self, tokens, tiles: dict, owner=None) -> bool:
        """Store one demoted page: ``tokens`` is the full token prefix
        the tree entry cached (its identity), ``tiles`` the page's raw
        host arrays. Over-budget puts prune LRU (unpinned) entries; a
        page larger than the whole budget is skipped, counted, never an
        error. ``owner`` (optional tenant id) bills the page's bytes in
        ``owner_bytes`` for as long as it is resident at this rung.
        Returns whether the page was kept."""
        toks = tuple(int(t) for t in np.asarray(tokens).reshape(-1))
        nbytes = sum(int(v.nbytes) for v in tiles.values())
        if nbytes > self.capacity_bytes:
            self.demote_skips += 1
            self._count(f"Serve/{self.kind}_demote_skips")
            return False
        key = (len(toks), token_hash(toks))
        old = self.entries.get(key)
        if old is not None:
            if old["pinned"]:
                # an in-flight admission pinned this key (match() →
                # consume() within the same try_admit; the demotion
                # running between them is that admission's own eviction
                # pass) — replacing it would void the pin and let a
                # later prune drop the entry mid-restore. Keep the
                # pinned entry; skip the demotion.
                self.demote_skips += 1
                self._count(f"Serve/{self.kind}_demote_skips")
                return False
            self.entries.pop(key)
            self.bytes_used -= old["nbytes"]
            self._owner_delta(old.get("owner"), -old["nbytes"])
            self._discard(old)
        ent = {
            "tokens": toks, "tiles": None, "nbytes": nbytes,
            "crc": tiles_crc(tiles), "t": self.clock(), "pinned": False,
            "owner": owner,
        }
        self._attach(key, ent, tiles)
        self.entries[key] = ent
        self.bytes_used += nbytes
        self._owner_delta(owner, nbytes)
        self.demotes += 1
        self.demote_bytes += nbytes
        self._count(f"Serve/{self.kind}_demotes")
        self._count(f"Serve/{self.kind}_demote_bytes", nbytes)
        self._prune()
        self._publish()
        return True

    def holds(self, tokens, key=None) -> bool:
        """Exact membership probe (key + tail-token verification, no
        payload touch): is this full prefix already resident here? The
        demote-ahead lane uses it to skip re-staging and to turn a
        later eviction of a staged page into a pure refcount drop.
        Callers that already computed the ghost-list key pass it via
        ``key`` to skip the token re-hash on the admission path."""
        toks = tuple(int(t) for t in np.asarray(tokens).reshape(-1))
        if key is None:
            key = (len(toks), token_hash(toks))
        ent = self.entries.get(key)
        return ent is not None and ent["tokens"] == toks

    def _prune(self) -> None:
        """LRU-drop unpinned entries until the budget holds. Pinned
        entries (matched, awaiting consume in this very admission) are
        skipped — at most ``pages_per_slot`` of them exist at a time.
        With a rung below wired (``spill_to``), each victim that still
        verifies is handed DOWN instead of vanishing — host prune
        becomes the NVMe rung's demotion feed."""
        while self.bytes_used > self.capacity_bytes:
            victim = None
            for key, ent in self.entries.items():
                if not ent["pinned"]:
                    victim = key
                    break
            if victim is None:
                return
            ent = self.entries.pop(victim)
            self.bytes_used -= ent["nbytes"]
            self._owner_delta(ent.get("owner"), -ent["nbytes"])
            self.prunes += 1
            self.pruned_bytes += ent["nbytes"]
            self._count(f"Serve/{self.kind}_prunes")
            if self.spill_to is not None:
                tiles = self._verify(ent)
                if tiles is not None and self.spill_to.put(
                        ent["tokens"], tiles, owner=ent.get("owner")):
                    self.spills += 1
                    self._count(f"Serve/{self.kind}_spills")
            self._discard(ent)

    # -------------------------------------------------------------- restore
    def _tail_mismatch(self, ent: dict, toks, length: int) -> bool:
        """Exact verification of the entry's OWN block (its last
        ``page_size`` tokens) against the prompt. The earlier prefix is
        covered by induction: blocks below ``start_block`` were matched
        token-exact by the radix tree, each prior tier hit verified its
        own block, and the ``(prefix_len, rolling_hash)`` key ties the
        whole prefix (the same identity standard the ghost ledger uses
        alone). A full-prefix tuple compare per block would be
        O(P²/page_size) on the admission/routing paths."""
        ps = self.page_size
        return ent["tokens"][length - ps:] != tuple(
            int(t) for t in toks[length - ps:length])

    def match_one(self, key, toks, length: int) -> str:
        """Probe ONE block key. ``"hit"`` pins the entry (payload
        verified, tiles staged for consume); ``"absent"`` /
        ``"collision"`` are misses; ``"corrupt"`` means the payload
        failed verification — the entry is dropped and the fallback
        counted, the caller recomputes the block."""
        ent = self.entries.get(key)
        if ent is None:
            return "absent"
        if self._tail_mismatch(ent, toks, length):
            # rolling-hash collision: not this prefix — a miss
            return "collision"
        if self._verify(ent) is None:
            # corrupt/torn/missing copy: drop it and recompute the
            # block — the tier degrades, serving never crashes
            self.entries.pop(key, None)
            self.bytes_used -= ent["nbytes"]
            self._owner_delta(ent.get("owner"), -ent["nbytes"])
            self.fallbacks += 1
            self._count(f"Serve/{self.kind}_fallbacks")
            self._discard(ent)
            self._publish()
            return "corrupt"
        ent["pinned"] = True
        self.entries.move_to_end(key)
        return "hit"

    def peek_one(self, key, toks, length: int) -> bool:
        """Read-only single-block residency probe (no pins, no LRU
        touch, no payload verification — routing must stay cheap)."""
        ent = self.entries.get(key)
        return ent is not None and not self._tail_mismatch(ent, toks,
                                                           length)

    def match(self, prompt, start_block: int,
              max_blocks: Optional[int] = None) -> list:
        """Consecutive full-block continuations of a tree match held
        here: walk the prompt's block boundaries from ``start_block``,
        verify each candidate's tokens (hash collisions are misses)
        and payload CRC (corruption is a counted fallback, the entry
        dropped), PIN every hit, and return its keys in block order.
        The first gap ends the run — a restore must extend the seated
        prefix contiguously."""
        toks = np.asarray(prompt).reshape(-1)
        keys: list = []
        if not self.entries:
            return keys
        for b, (length, h) in enumerate(prefix_hashes(toks,
                                                      self.page_size)):
            if b < start_block:
                continue
            if max_blocks is not None and len(keys) >= max_blocks:
                break
            r = self.match_one((length, h), toks, length)
            if r == "hit":
                keys.append((length, h))
                continue
            if r == "collision" or (r == "absent" and b == start_block):
                self.misses += 1
                self._count(f"Serve/{self.kind}_misses")
            break
        return keys

    def peek_blocks(self, prompt, start_block: int) -> int:
        """Read-only residency probe for the fleet router: how many
        consecutive full blocks past ``start_block`` the tier holds. No
        pins, no LRU touch, no CRC pass — routing must stay cheap."""
        if not self.entries:
            return 0
        toks = np.asarray(prompt).reshape(-1)
        n = 0
        for b, (length, h) in enumerate(prefix_hashes(toks,
                                                      self.page_size)):
            if b < start_block:
                continue
            if not self.peek_one((length, h), toks, length):
                break
            n += 1
        return n

    def _pop(self, key) -> dict:
        """Pop one pinned match for consumption: the entry leaves the
        store (its payload storage reclaimed) but its verified tiles —
        staged by ``match_one`` — ride out on the returned entry."""
        ent = self.entries.pop(key)
        self.bytes_used -= ent["nbytes"]
        self._owner_delta(ent.get("owner"), -ent["nbytes"])
        self.hits += 1
        self._count(f"Serve/{self.kind}_hits")
        self._discard(ent)
        return ent

    def consume(self, keys: list) -> tuple:
        """Pop the pinned matches of one admission into a stacked
        payload ``{k: (L, R, KV, ps, hd), ...}`` (R = len(keys), block
        order) — the restore scatter's input. Returns ``(tiles, nbytes,
        tokens)``."""
        ents = [self._pop(k) for k in keys]
        nbytes = sum(e["nbytes"] for e in ents)
        tiles = {name: np.stack([e["tiles"][name] for e in ents], axis=1)
                 for name in ents[0]["tiles"]}
        self._publish()
        return tiles, nbytes, len(ents) * self.page_size

    def release(self, keys: list) -> None:
        """Unpin matched entries without consuming them — the admission
        deferred (transient pool pressure); the blocks stay restorable
        for the retry."""
        for k in keys:
            ent = self.entries.get(k)
            if ent is not None:
                ent["pinned"] = False
                self._unfetch(ent)

    def on_restore(self, wall_s: float, pages: int, tokens: int,
                   nbytes: int) -> None:
        """Achieved accounting for one dispatched restore (the engine's
        measured dispatch window — honest on CPU, a lower bound where
        the scatter overlaps the async device queue)."""
        self.restores += 1
        self.restored_pages += pages
        self.restored_tokens += tokens
        self.restore_bytes += nbytes
        self.restore_wait_s += wall_s
        self._count(f"Serve/{self.kind}_restores")
        self._count(f"Serve/{self.kind}_restored_pages", pages)
        self._count(f"Serve/{self.kind}_restored_tokens", tokens)
        self._count(f"Serve/{self.kind}_restore_bytes", nbytes)
        if self.registry is not None:
            self.registry.histogram(
                f"Serve/{self.kind}_restore_wait_s").observe(wall_s)
        self._publish()

    # -------------------------------------------------------------- readout
    def _snapshot_extra(self) -> dict:
        return {}

    def snapshot(self) -> dict:
        """Flight-recorder provider + this rung's section of
        ``kv_residency()`` / the capacity report's achieved side."""
        self._publish()
        out = {
            "pages": len(self.entries),
            "bytes": self.bytes_used,
            "capacity_bytes": self.capacity_bytes,
            "occupancy": self.bytes_used / self.capacity_bytes,
            "pressure": self.pressure,
            "page_size": self.page_size,
            "demotes": self.demotes,
            "demote_bytes": self.demote_bytes,
            "demote_skips": self.demote_skips,
            "restores": self.restores,
            "restored_pages": self.restored_pages,
            "restored_tokens": self.restored_tokens,
            "restore_bytes": self.restore_bytes,
            "restore_wait_s": self.restore_wait_s,
            "restore_tokens_per_s": (
                self.restored_tokens / self.restore_wait_s
                if self.restore_wait_s > 0 else None),
            "hits": self.hits,
            "misses": self.misses,
            "prunes": self.prunes,
            "pruned_bytes": self.pruned_bytes,
            "spills": self.spills,
            "fallbacks": self.fallbacks,
            "owner_bytes": dict(self.owner_bytes),
        }
        out.update(self._snapshot_extra())
        return out


class NVMeKVTier(TierStore):
    """The disk rung: demoted pages persist as one swap file per block
    under an :class:`~..ops.aio.AIOFileStore` (the same seam the
    optimizer-state offload swaps through).

    - **put** serializes the page's tiles into one flat buffer (sorted
      tile-name order, so the whole-file crc32 equals the shared
      :func:`tiles_crc`) and submits an ASYNC write — write-behind
      depth ``write_behind`` bounds in-flight buffers, so demotion
      spills stream to disk without blocking the serving iteration.
      Dtype/shape specs stay in RAM (bytes on disk, layout in the
      index) — a few hundred bytes per resident block.
    - **match** performs the verified read: wait the entry's own
      pending write (if any), read the file into a zeroed staging
      buffer (a torn/short file therefore deterministically fails the
      CRC), verify, and slice the tiles back as views. Any I/O error or
      checksum mismatch is a counted fallback — recompute, never crash.
    - Each tier instance owns a UNIQUE subdirectory (two replicas
      sharing one NVMe mount never collide), created under
      ``serving.nvme_path`` (default ``$TMPDIR/dstpu_kv_nvme``).
    """

    kind = "nvme_tier"

    def __init__(self, capacity_bytes: int, page_size: int,
                 path: Optional[str] = None, registry=None,
                 clock: Optional[Callable] = None, n_threads: int = 2,
                 write_behind: int = 1, use_direct: bool = False):
        from ..ops import aio as aio_mod
        root = path or os.path.join(tempfile.gettempdir(), "dstpu_kv_nvme")
        os.makedirs(root, exist_ok=True)
        self.dir = tempfile.mkdtemp(prefix="kv_", dir=root)
        self.store = aio_mod.AIOFileStore(self.dir, n_threads=n_threads,
                                          use_direct=use_direct)
        self.write_behind = max(0, int(write_behind))
        self._pending: List[dict] = []   # entries with in-flight writes
        self.promotions = 0              # blocks read back (disk -> host)
        self.read_bytes = 0
        self.read_wait_s = 0.0
        self.write_bytes = 0
        super().__init__(capacity_bytes, page_size, registry=registry,
                         clock=clock)

    # ---------------------------------------------------- payload transport
    @staticmethod
    def _file(key) -> str:
        return f"b{key[0]}_{key[1]:016x}.bin"

    def _attach(self, key, ent: dict, tiles: dict) -> None:
        specs, arrs, off = [], [], 0
        for name in sorted(tiles):
            a = np.ascontiguousarray(tiles[name])
            specs.append((name, a.dtype, a.shape, off, int(a.nbytes)))
            arrs.append(a)
            off += int(a.nbytes)
        buf = np.empty(off, np.uint8)
        for (name, dt, shp, o, nb), a in zip(specs, arrs):
            buf[o:o + nb] = a.view(np.uint8).reshape(-1)
        ent["file"] = self._file(key)
        ent["specs"] = specs
        ent["buf"] = buf   # MUST outlive the async write (native aio
        #                    holds the raw pointer until waited)
        try:
            ent["ticket"] = self.store.submit_write(ent["file"], buf)
        except OSError:
            # submit failure (store counted it): leave no ticket — the
            # read path will miss the file and degrade to recompute
            ent.pop("buf", None)
            return
        self.write_bytes += int(buf.nbytes)
        self._count(f"Serve/{self.kind}_write_bytes", int(buf.nbytes))
        self._pending.append(ent)
        self._settle(self.write_behind)

    def _settle_ent(self, ent: dict) -> None:
        t = ent.pop("ticket", None)
        if t is not None:
            try:
                self.store.wait(t)
            except OSError:
                pass   # counted by the store; the read path verifies
        ent.pop("buf", None)

    def _settle(self, keep: int) -> None:
        while len(self._pending) > keep:
            self._settle_ent(self._pending.pop(0))

    def flush(self) -> None:
        """Wait out every in-flight write (tests / shutdown)."""
        self._settle(0)

    def _verify(self, ent: dict):
        tiles = ent.get("tiles")
        if tiles is not None:
            return tiles
        if "ticket" in ent or "buf" in ent:
            self._pending = [p for p in self._pending if p is not ent]
            self._settle_ent(ent)
        if "file" not in ent:
            return None
        # zeroed staging: a torn/short file leaves trailing zeros and
        # deterministically fails the CRC below
        buf = np.zeros(ent["nbytes"], np.uint8)
        t0 = self.clock()
        try:
            self.store.sync_read(ent["file"], buf)
        except OSError:
            return None
        wall = max(0.0, self.clock() - t0)
        if zlib.crc32(buf) != ent["crc"]:
            return None
        tiles = {name: buf[off:off + nb].view(dt).reshape(shp)
                 for name, dt, shp, off, nb in ent["specs"]}
        ent["tiles"] = tiles
        self.promotions += 1
        self.read_bytes += int(ent["nbytes"])
        self.read_wait_s += wall
        self._count(f"Serve/{self.kind}_promotions")
        self._count(f"Serve/{self.kind}_read_bytes", int(ent["nbytes"]))
        return tiles

    def _unfetch(self, ent: dict) -> None:
        ent["tiles"] = None   # drop the staged read; the file remains

    def _discard(self, ent: dict) -> None:
        self._pending = [p for p in self._pending if p is not ent]
        self._settle_ent(ent)
        f = ent.pop("file", None)
        if f is not None:
            self.store.unlink(f)

    # ------------------------------------------------------------- metrics
    def _publish(self) -> None:
        super()._publish()
        if self.registry is not None:
            self.registry.set_gauges({
                "Serve/nvme_aio_errors": float(self.store.errors),
            })

    def _snapshot_extra(self) -> dict:
        return {
            "promotions": self.promotions,
            "read_bytes": self.read_bytes,
            "read_wait_s": self.read_wait_s,
            "read_mb_s": (self.read_bytes / self.read_wait_s / 1e6
                          if self.read_wait_s > 0 and self.read_bytes
                          else None),
            "write_bytes": self.write_bytes,
            "pending_writes": len(self._pending),
            "aio_errors": self.store.errors,
            "native_aio": bool(self.store.aio._lib is not None),
        }

    def close(self) -> None:
        self.flush()
        self.store.close()


class TieringEngine:
    """Ranked-store coordinator speaking the exact ``pool.host``
    protocol (put / match / peek_blocks / consume / release /
    on_restore / holds / snapshot), so :class:`~.pages.PagePool` and
    :class:`~.engine.ServingEngine` stay rung-count-agnostic.

    - Demotions **put** into the top rung; its pin-aware LRU prune
      spills victims downward (``spill_to`` chain wired here) — cold
      history cascades HBM → host → NVMe instead of vanishing.
    - **match** walks the prompt's block boundaries once and probes
      rungs in rank order per block (a host hit beats a disk hit; a
      corrupt copy at one rung still lets a lower rung serve the same
      block). Hits are pinned where they live; keys are ``(rank,
      store_key)`` so consume/release dispatch without a search.
    - **consume** stacks mixed-rung blocks into ONE restore payload —
      an NVMe block's verified read happened at match time, so the
      restore scatter is the same single program regardless of where
      each block slept.
    """

    def __init__(self, stores: List[TierStore]):
        if not stores:
            raise ValueError("TieringEngine needs at least one store")
        self.stores = list(stores)
        for up, down in zip(self.stores, self.stores[1:]):
            up.spill_to = down
        self.page_size = self.stores[0].page_size

    @property
    def pressure(self) -> bool:
        return self.stores[0].pressure

    def put(self, tokens, tiles: dict, owner=None) -> bool:
        return self.stores[0].put(tokens, tiles, owner=owner)

    def holds(self, tokens, key=None) -> bool:
        return any(st.holds(tokens, key=key) for st in self.stores)

    def match(self, prompt, start_block: int,
              max_blocks: Optional[int] = None) -> list:
        toks = np.asarray(prompt).reshape(-1)
        keys: list = []
        if not any(st.entries for st in self.stores):
            return keys
        for b, (length, h) in enumerate(prefix_hashes(toks,
                                                      self.page_size)):
            if b < start_block:
                continue
            if max_blocks is not None and len(keys) >= max_blocks:
                break
            hit_rank = None
            for rank, st in enumerate(self.stores):
                if st.match_one((length, h), toks, length) == "hit":
                    hit_rank = rank
                    break
            if hit_rank is None:
                if b == start_block:
                    top = self.stores[0]
                    top.misses += 1
                    top._count(f"Serve/{top.kind}_misses")
                break
            keys.append((hit_rank, (length, h)))
        return keys

    def peek_blocks(self, prompt, start_block: int) -> int:
        if not any(st.entries for st in self.stores):
            return 0
        toks = np.asarray(prompt).reshape(-1)
        n = 0
        for b, (length, h) in enumerate(prefix_hashes(toks,
                                                      self.page_size)):
            if b < start_block:
                continue
            if not any(st.peek_one((length, h), toks, length)
                       for st in self.stores):
                break
            n += 1
        return n

    def consume(self, keys: list) -> tuple:
        ents = [self.stores[rank]._pop(key) for rank, key in keys]
        nbytes = sum(e["nbytes"] for e in ents)
        tiles = {name: np.stack([e["tiles"][name] for e in ents], axis=1)
                 for name in ents[0]["tiles"]}
        for st in self.stores:
            st._publish()
        return tiles, nbytes, len(ents) * self.page_size

    def release(self, keys: list) -> None:
        for rank, key in keys:
            self.stores[rank].release([key])

    def on_restore(self, wall_s: float, pages: int, tokens: int,
                   nbytes: int) -> None:
        self.stores[0].on_restore(wall_s, pages, tokens, nbytes)

    def snapshot(self) -> dict:
        """Top rung's snapshot with each lower rung nested under its
        ``kind`` — the shape ``kv_residency()``/health() attach."""
        out = self.stores[0].snapshot()
        for st in self.stores[1:]:
            out[st.kind] = st.snapshot()
        return out
