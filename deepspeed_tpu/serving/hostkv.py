"""Tiered KV: a bounded pinned-host page store behind the device pool.

The ROADMAP's tiered-KV wall, built on the measurements PR 13 shipped:
ZeRO-Infinity's memory-wall playbook (PAPERS.md) applied to the paged KV
cache. Today ``PagePool._evict`` reclaims cold tree-held pages by
dropping them, and the next admission of the same prefix re-pays its
whole prefill — the regret kvscope's ghost ledger counts. With a host
tier (``serving.host_pool_bytes``), eviction instead DEMOTES:

- **demote-on-evict** — the pool's ``on_demote`` seam hands the engine
  every evicted full-block tree entry (its page id + the radix-tree
  token prefix that keys it); the engine DISPATCHES a gather of those
  pages' tiles (K, V, and the int8 scale planes when the pool is
  quantized) with ONE fixed-shape program (:func:`demote_rows`, row
  padded with the scratch page) right there — dispatch order is the
  safety: the gather executes ahead of any later-dispatched insert that
  could rewrite the freed pages — and materializes the bytes into this
  store at the END of the serving iteration
  (``ServingEngine._drain_demotes``), keyed exactly like the ghost
  list: ``(prefix_len, prefix_hash)`` with the full token tuple kept
  for verification. Demotion therefore never bills the resuming
  request's TTFT, and rides only the eviction-pressure path.
- **restore-on-resume** — admission consults the tier right after the
  radix-tree match (:meth:`HostKVTier.match`: consecutive full-block
  continuations of the tree hit, token-verified, checksum-verified);
  matched cold blocks are CONSUMED and their tiles scattered into the
  request's prefill cache (:func:`restore_into_cache`) in up to two
  fixed-shape batches, so the second host→device upload overlaps the
  first batch's device write and the whole restore overlaps the
  unshared-suffix prefill chunks behind it (async dispatch). From there
  the request flows through the SAME ``plan_chunks(skip=)`` → hydrate →
  ``insert_paged`` path as a tree hit — the restore is a data question,
  zero new steady-state programs beyond the one restore scatter.
- **degrade, never crash** — a pruned, collision-shadowed, or
  checksum-corrupt host copy is simply not a match: the block stays in
  the chunk plan and is recomputed (corruption counted in
  ``Serve/host_tier_fallbacks``). A restore abandoned mid-admission
  (deferred allocation) releases its pins; a cancelled restored request
  loses the cold copy and later resumes recompute — correctness never
  depends on the tier holding anything.

Capacity is a byte budget (``host_pool_bytes``): the store is LRU — a
put over budget prunes the coldest entries (``Serve/host_tier_prunes``)
until the new page fits. Everything host-side here is numpy + dicts; the
two device programs are compiled once and live in the engine's shared
program LRU (a fleet's replicas reuse them like every other program).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..inference.decode import dequantize_kv
from .tiering import TierStore, tiles_crc

__all__ = ["HostKVTier", "demote_rows", "restore_into_cache"]


# ------------------------------------------------------------ device side
def demote_rows(state, row):
    """Gather ``row``'s pool page tiles for host demotion: K, V, and the
    int8 scale planes when the pool is quantized. ``row`` is a full
    ``(pages_per_slot,)`` id vector padded with the scratch page, so ONE
    compiled program serves every eviction batch — padding entries are
    dropped host-side. MUST stay read-only (no donation) and MUST be
    dispatched before any program that could rewrite the freed pages
    (the engine dispatches it inside the eviction pass, before the
    admission's insert exists): dispatch order — not blocking — is what
    keeps the gathered bytes pristine while the ``device_get`` is
    deferred to end-of-iteration."""
    c = state.cache
    out = {"k": c.k[:, row], "v": c.v[:, row]}   # (L, n, KV, ps, hd)
    if c.k_scale is not None:
        out["k_scale"] = c.k_scale[:, row]       # (L, n, KV, ps)
        out["v_scale"] = c.v_scale[:, row]
    return out


def restore_into_cache(cache, tiles, start, count):
    """Scatter ``count`` host-restored page tiles into a batch-1 prefill
    cache's pages ``[start, start + count)`` — the restore-side analog
    of :func:`~.pages.hydrate_cache`, reading host bytes instead of pool
    pages. ``tiles`` is a fixed-size batch (padding entries masked by
    ``count``), so one compiled program serves every restore; int8 tiles
    dequantize here with the same point-of-use spelling the hydrate
    gather uses (:func:`~..inference.decode.dequantize_kv`), and the
    suffix prefill then runs in the compute dtype exactly as a tree
    hit's would."""
    from .pages import _page_merge, _page_split

    R, ps = tiles["k"].shape[1], tiles["k"].shape[3]
    max_len = cache.k.shape[3]
    n = max_len // ps
    if "k_scale" in tiles:
        tk = dequantize_kv(tiles["k"], tiles["k_scale"], cache.k.dtype)
        tv = dequantize_kv(tiles["v"], tiles["v_scale"], cache.v.dtype)
    else:
        tk = tiles["k"].astype(cache.k.dtype)
        tv = tiles["v"].astype(cache.v.dtype)
    keep = jnp.arange(R) < count
    # masked (padding) entries point past the last page: mode="drop"
    # discards them — no read-modify-write, no duplicate-index hazard
    tgt = jnp.where(keep, start + jnp.arange(R), n)
    ck = _page_split(cache.k, n, ps).at[:, tgt].set(tk, mode="drop")
    cv = _page_split(cache.v, n, ps).at[:, tgt].set(tv, mode="drop")
    return cache._replace(k=_page_merge(ck, cache.k),
                          v=_page_merge(cv, cache.v))


# -------------------------------------------------------------- host side
# shared integrity checksum (one CRC contract across every rung)
_crc = tiles_crc


class HostKVTier(TierStore):
    """Bounded host-memory page store: the demotion target and restore
    source for one engine's :class:`~.pages.PagePool` — the DRAM rung
    of the :mod:`~.tiering` hierarchy.

    Entries are one full tree block each — ``(prefix_len, prefix_hash)``
    key (the ghost-list spelling, via the shared
    :func:`~..observability.workload.token_hash`), the full token tuple
    for exact verification, the page's raw tiles, and a CRC. ``match``
    walks an admitted prompt's block boundaries past the tree hit and
    PINS consecutive matches (a concurrent demotion's prune cannot drop
    a block mid-admission); ``consume`` pops pinned matches into one
    stacked payload; ``release`` unpins when the allocation deferred.
    All ``Serve/host_tier_*`` metrics land in the serving registry.

    Every store behavior — LRU budget, pins, the match/consume/release
    handshake, degrade-never-crash — is the shared
    :class:`~.tiering.TierStore` implementation; this rung's payload
    transport is trivial: tiles simply stay in RAM on the entry."""

    kind = "host_tier"

    # ---------------------------------------------------- payload transport
    def _attach(self, key, ent: dict, tiles: dict) -> None:
        ent["tiles"] = tiles

    def _verify(self, ent: dict):
        return ent["tiles"] if tiles_crc(ent["tiles"]) == ent["crc"] \
            else None

