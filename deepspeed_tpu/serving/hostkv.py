"""Tiered KV: a bounded pinned-host page store behind the device pool.

The ROADMAP's tiered-KV wall, built on the measurements PR 13 shipped:
ZeRO-Infinity's memory-wall playbook (PAPERS.md) applied to the paged KV
cache. Today ``PagePool._evict`` reclaims cold tree-held pages by
dropping them, and the next admission of the same prefix re-pays its
whole prefill — the regret kvscope's ghost ledger counts. With a host
tier (``serving.host_pool_bytes``), eviction instead DEMOTES:

- **demote-on-evict** — the pool's ``on_demote`` seam hands the engine
  every evicted full-block tree entry (its page id + the radix-tree
  token prefix that keys it); the engine DISPATCHES a gather of those
  pages' tiles (K, V, and the int8 scale planes when the pool is
  quantized) with ONE fixed-shape program (:func:`demote_rows`, row
  padded with the scratch page) right there — dispatch order is the
  safety: the gather executes ahead of any later-dispatched insert that
  could rewrite the freed pages — and materializes the bytes into this
  store at the END of the serving iteration
  (``ServingEngine._drain_demotes``), keyed exactly like the ghost
  list: ``(prefix_len, prefix_hash)`` with the full token tuple kept
  for verification. Demotion therefore never bills the resuming
  request's TTFT, and rides only the eviction-pressure path.
- **restore-on-resume** — admission consults the tier right after the
  radix-tree match (:meth:`HostKVTier.match`: consecutive full-block
  continuations of the tree hit, token-verified, checksum-verified);
  matched cold blocks are CONSUMED and their tiles scattered into the
  request's prefill cache (:func:`restore_into_cache`) in up to two
  fixed-shape batches, so the second host→device upload overlaps the
  first batch's device write and the whole restore overlaps the
  unshared-suffix prefill chunks behind it (async dispatch). From there
  the request flows through the SAME ``plan_chunks(skip=)`` → hydrate →
  ``insert_paged`` path as a tree hit — the restore is a data question,
  zero new steady-state programs beyond the one restore scatter.
- **degrade, never crash** — a pruned, collision-shadowed, or
  checksum-corrupt host copy is simply not a match: the block stays in
  the chunk plan and is recomputed (corruption counted in
  ``Serve/host_tier_fallbacks``). A restore abandoned mid-admission
  (deferred allocation) releases its pins; a cancelled restored request
  loses the cold copy and later resumes recompute — correctness never
  depends on the tier holding anything.

Capacity is a byte budget (``host_pool_bytes``): the store is LRU — a
put over budget prunes the coldest entries (``Serve/host_tier_prunes``)
until the new page fits. Everything host-side here is numpy + dicts; the
two device programs are compiled once and live in the engine's shared
program LRU (a fleet's replicas reuse them like every other program).
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from ..inference.decode import dequantize_kv
from ..observability.workload import prefix_hashes, token_hash

__all__ = ["HostKVTier", "demote_rows", "restore_into_cache"]


# ------------------------------------------------------------ device side
def demote_rows(state, row):
    """Gather ``row``'s pool page tiles for host demotion: K, V, and the
    int8 scale planes when the pool is quantized. ``row`` is a full
    ``(pages_per_slot,)`` id vector padded with the scratch page, so ONE
    compiled program serves every eviction batch — padding entries are
    dropped host-side. MUST stay read-only (no donation) and MUST be
    dispatched before any program that could rewrite the freed pages
    (the engine dispatches it inside the eviction pass, before the
    admission's insert exists): dispatch order — not blocking — is what
    keeps the gathered bytes pristine while the ``device_get`` is
    deferred to end-of-iteration."""
    c = state.cache
    out = {"k": c.k[:, row], "v": c.v[:, row]}   # (L, n, KV, ps, hd)
    if c.k_scale is not None:
        out["k_scale"] = c.k_scale[:, row]       # (L, n, KV, ps)
        out["v_scale"] = c.v_scale[:, row]
    return out


def restore_into_cache(cache, tiles, start, count):
    """Scatter ``count`` host-restored page tiles into a batch-1 prefill
    cache's pages ``[start, start + count)`` — the restore-side analog
    of :func:`~.pages.hydrate_cache`, reading host bytes instead of pool
    pages. ``tiles`` is a fixed-size batch (padding entries masked by
    ``count``), so one compiled program serves every restore; int8 tiles
    dequantize here with the same point-of-use spelling the hydrate
    gather uses (:func:`~..inference.decode.dequantize_kv`), and the
    suffix prefill then runs in the compute dtype exactly as a tree
    hit's would."""
    from .pages import _page_merge, _page_split

    R, ps = tiles["k"].shape[1], tiles["k"].shape[3]
    max_len = cache.k.shape[3]
    n = max_len // ps
    if "k_scale" in tiles:
        tk = dequantize_kv(tiles["k"], tiles["k_scale"], cache.k.dtype)
        tv = dequantize_kv(tiles["v"], tiles["v_scale"], cache.v.dtype)
    else:
        tk = tiles["k"].astype(cache.k.dtype)
        tv = tiles["v"].astype(cache.v.dtype)
    keep = jnp.arange(R) < count
    # masked (padding) entries point past the last page: mode="drop"
    # discards them — no read-modify-write, no duplicate-index hazard
    tgt = jnp.where(keep, start + jnp.arange(R), n)
    ck = _page_split(cache.k, n, ps).at[:, tgt].set(tk, mode="drop")
    cv = _page_split(cache.v, n, ps).at[:, tgt].set(tv, mode="drop")
    return cache._replace(k=_page_merge(ck, cache.k),
                          v=_page_merge(cv, cache.v))


# -------------------------------------------------------------- host side
def _crc(tiles: dict) -> int:
    """Integrity checksum over a page's raw host bytes: a corrupt or
    torn host copy must degrade to recompute, never into the cache."""
    h = 0
    for key in sorted(tiles):
        h = zlib.crc32(np.ascontiguousarray(tiles[key]).tobytes(), h)
    return h


class HostKVTier:
    """Bounded host-memory page store: the demotion target and restore
    source for one engine's :class:`~.pages.PagePool`.

    Entries are one full tree block each — ``(prefix_len, prefix_hash)``
    key (the ghost-list spelling, via the shared
    :func:`~..observability.workload.token_hash`), the full token tuple
    for exact verification, the page's raw tiles, and a CRC. ``match``
    walks an admitted prompt's block boundaries past the tree hit and
    PINS consecutive matches (a concurrent demotion's prune cannot drop
    a block mid-admission); ``consume`` pops pinned matches into one
    stacked payload; ``release`` unpins when the allocation deferred.
    All ``Serve/host_tier_*`` metrics land in the serving registry."""

    def __init__(self, capacity_bytes: int, page_size: int,
                 registry=None, clock: Optional[Callable] = None):
        if capacity_bytes < 1:
            raise ValueError(f"host_pool_bytes must be >= 1, "
                             f"got {capacity_bytes}")
        self.capacity_bytes = int(capacity_bytes)
        self.page_size = int(page_size)
        self.registry = registry
        self.clock = clock if clock is not None else (lambda: 0.0)
        self.entries: OrderedDict = OrderedDict()
        self.bytes_used = 0
        # cumulative accounting (the capacity advisor's achieved side)
        self.demotes = 0            # pages demoted into the tier
        self.demote_bytes = 0
        self.demote_skips = 0       # pages too large for the whole budget
        self.restores = 0           # restore OPERATIONS (one per admission)
        self.restored_pages = 0
        self.restored_tokens = 0
        self.restore_bytes = 0
        self.restore_wait_s = 0.0   # summed dispatch wall of all restores
        self.hits = 0               # blocks served from the tier
        self.misses = 0             # continuation probes that found nothing
        self.prunes = 0             # entries LRU-dropped for capacity
        self.pruned_bytes = 0
        self.fallbacks = 0          # corrupt/mismatched copies -> recompute
        self._publish()

    # ------------------------------------------------------------- metrics
    def _publish(self) -> None:
        if self.registry is None:
            return
        self.registry.set_gauges({
            "Serve/host_tier_pages": float(len(self.entries)),
            "Serve/host_tier_bytes": float(self.bytes_used),
            "Serve/host_tier_capacity_bytes": float(self.capacity_bytes),
            "Serve/host_tier_occupancy": (
                self.bytes_used / self.capacity_bytes),
            "Serve/host_tier_pressure": float(self.pressure),
        })

    def _count(self, name: str, n: int = 1) -> None:
        if self.registry is not None and n:
            self.registry.counter(name).inc(n)

    @property
    def pressure(self) -> bool:
        """True when the tier cannot fit another typical page without
        pruning a cold one — the next demotion starts losing history."""
        if not self.entries:
            return False
        mean = self.bytes_used / len(self.entries)
        return self.capacity_bytes - self.bytes_used < mean

    # ------------------------------------------------------------- demotion
    def put(self, tokens, tiles: dict) -> bool:
        """Store one demoted page: ``tokens`` is the full token prefix
        the tree entry cached (its identity), ``tiles`` the page's raw
        host arrays. Over-budget puts prune LRU (unpinned) entries; a
        page larger than the whole budget is skipped, counted, never an
        error. Returns whether the page was kept."""
        toks = tuple(int(t) for t in np.asarray(tokens).reshape(-1))
        nbytes = sum(int(v.nbytes) for v in tiles.values())
        if nbytes > self.capacity_bytes:
            self.demote_skips += 1
            self._count("Serve/host_tier_demote_skips")
            return False
        key = (len(toks), token_hash(toks))
        old = self.entries.get(key)
        if old is not None:
            if old["pinned"]:
                # an in-flight admission pinned this key (match() →
                # consume() within the same try_admit; the demotion
                # running between them is that admission's own eviction
                # pass) — replacing it would void the pin and let a
                # later prune drop the entry mid-restore. Keep the
                # pinned entry; skip the demotion.
                self.demote_skips += 1
                self._count("Serve/host_tier_demote_skips")
                return False
            self.entries.pop(key)
            self.bytes_used -= old["nbytes"]
        self.entries[key] = {
            "tokens": toks, "tiles": tiles, "nbytes": nbytes,
            "crc": _crc(tiles), "t": self.clock(), "pinned": False,
        }
        self.bytes_used += nbytes
        self.demotes += 1
        self.demote_bytes += nbytes
        self._count("Serve/host_tier_demotes")
        self._count("Serve/host_tier_demote_bytes", nbytes)
        self._prune()
        self._publish()
        return True

    def _prune(self) -> None:
        """LRU-drop unpinned entries until the budget holds. Pinned
        entries (matched, awaiting consume in this very admission) are
        skipped — at most ``pages_per_slot`` of them exist at a time."""
        while self.bytes_used > self.capacity_bytes:
            victim = None
            for key, ent in self.entries.items():
                if not ent["pinned"]:
                    victim = key
                    break
            if victim is None:
                return
            ent = self.entries.pop(victim)
            self.bytes_used -= ent["nbytes"]
            self.prunes += 1
            self.pruned_bytes += ent["nbytes"]
            self._count("Serve/host_tier_prunes")

    # -------------------------------------------------------------- restore
    def _tail_mismatch(self, ent: dict, toks, length: int) -> bool:
        """Exact verification of the entry's OWN block (its last
        ``page_size`` tokens) against the prompt. The earlier prefix is
        covered by induction: blocks below ``start_block`` were matched
        token-exact by the radix tree, each prior host hit verified its
        own block, and the ``(prefix_len, rolling_hash)`` key ties the
        whole prefix (the same identity standard the ghost ledger uses
        alone). A full-prefix tuple compare per block would be
        O(P²/page_size) on the admission/routing paths."""
        ps = self.page_size
        return ent["tokens"][length - ps:] != tuple(
            int(t) for t in toks[length - ps:length])

    def match(self, prompt, start_block: int,
              max_blocks: Optional[int] = None) -> list:
        """Consecutive full-block continuations of a tree match held
        here: walk the prompt's block boundaries from ``start_block``,
        verify each candidate's tokens (hash collisions are misses)
        and CRC (corruption is a counted fallback, the entry dropped),
        PIN every hit, and return its keys in block order. The first
        gap ends the run — a restore must extend the seated prefix
        contiguously."""
        toks = np.asarray(prompt).reshape(-1)
        keys: list = []
        if not self.entries:
            return keys
        for b, (length, h) in enumerate(prefix_hashes(toks,
                                                      self.page_size)):
            if b < start_block:
                continue
            if max_blocks is not None and len(keys) >= max_blocks:
                break
            key = (length, h)
            ent = self.entries.get(key)
            if ent is None:
                if b == start_block:
                    self.misses += 1
                    self._count("Serve/host_tier_misses")
                break
            if self._tail_mismatch(ent, toks, length):
                # rolling-hash collision: not this prefix — a miss
                self.misses += 1
                self._count("Serve/host_tier_misses")
                break
            if _crc(ent["tiles"]) != ent["crc"]:
                # corrupt host copy: drop it and recompute the block —
                # the tier degrades, serving never crashes
                self.entries.pop(key)
                self.bytes_used -= ent["nbytes"]
                self.fallbacks += 1
                self._count("Serve/host_tier_fallbacks")
                self._publish()
                break
            ent["pinned"] = True
            self.entries.move_to_end(key)
            keys.append(key)
        return keys

    def peek_blocks(self, prompt, start_block: int) -> int:
        """Read-only residency probe for the fleet router: how many
        consecutive full blocks past ``start_block`` the tier holds. No
        pins, no LRU touch, no CRC pass — routing must stay cheap."""
        if not self.entries:
            return 0
        toks = np.asarray(prompt).reshape(-1)
        n = 0
        for b, (length, h) in enumerate(prefix_hashes(toks,
                                                      self.page_size)):
            if b < start_block:
                continue
            ent = self.entries.get((length, h))
            if ent is None or self._tail_mismatch(ent, toks, length):
                break
            n += 1
        return n

    def consume(self, keys: list) -> tuple:
        """Pop the pinned matches of one admission into a stacked
        payload ``{k: (L, R, KV, ps, hd), ...}`` (R = len(keys), block
        order) — the restore scatter's input. Returns ``(tiles, nbytes,
        tokens)``."""
        ents = [self.entries.pop(k) for k in keys]
        nbytes = sum(e["nbytes"] for e in ents)
        self.bytes_used -= nbytes
        self.hits += len(ents)
        self._count("Serve/host_tier_hits", len(ents))
        tiles = {name: np.stack([e["tiles"][name] for e in ents], axis=1)
                 for name in ents[0]["tiles"]}
        self._publish()
        return tiles, nbytes, len(ents) * self.page_size

    def release(self, keys: list) -> None:
        """Unpin matched entries without consuming them — the admission
        deferred (transient pool pressure); the blocks stay restorable
        for the retry."""
        for k in keys:
            ent = self.entries.get(k)
            if ent is not None:
                ent["pinned"] = False

    def on_restore(self, wall_s: float, pages: int, tokens: int,
                   nbytes: int) -> None:
        """Achieved accounting for one dispatched restore (the engine's
        measured dispatch window — honest on CPU, a lower bound where
        the scatter overlaps the async device queue)."""
        self.restores += 1
        self.restored_pages += pages
        self.restored_tokens += tokens
        self.restore_bytes += nbytes
        self.restore_wait_s += wall_s
        self._count("Serve/host_tier_restores")
        self._count("Serve/host_tier_restored_pages", pages)
        self._count("Serve/host_tier_restored_tokens", tokens)
        self._count("Serve/host_tier_restore_bytes", nbytes)
        if self.registry is not None:
            self.registry.histogram(
                "Serve/host_tier_restore_wait_s").observe(wall_s)
        self._publish()

    # -------------------------------------------------------------- readout
    def snapshot(self) -> dict:
        """Flight-recorder provider + the ``host_tier`` section of
        ``kv_residency()`` / the capacity report's achieved side."""
        self._publish()
        return {
            "pages": len(self.entries),
            "bytes": self.bytes_used,
            "capacity_bytes": self.capacity_bytes,
            "occupancy": self.bytes_used / self.capacity_bytes,
            "pressure": self.pressure,
            "page_size": self.page_size,
            "demotes": self.demotes,
            "demote_bytes": self.demote_bytes,
            "demote_skips": self.demote_skips,
            "restores": self.restores,
            "restored_pages": self.restored_pages,
            "restored_tokens": self.restored_tokens,
            "restore_bytes": self.restore_bytes,
            "restore_wait_s": self.restore_wait_s,
            "restore_tokens_per_s": (
                self.restored_tokens / self.restore_wait_s
                if self.restore_wait_s > 0 else None),
            "hits": self.hits,
            "misses": self.misses,
            "prunes": self.prunes,
            "pruned_bytes": self.pruned_bytes,
            "fallbacks": self.fallbacks,
        }
