"""Host-side continuous-batching scheduler: queue, slots, chunk plans.

Reference analog: DeepSpeed-MII / FastGen's Dynamic SplitFuse scheduler —
the policy half of continuous batching, split from the device half
(``slots.py`` / ``engine.py``) so it runs on plain numpy + floats and is
testable with a fake clock and no accelerator.

Policy, per serving iteration (see ``ServingEngine.step``):

1. admission — if a slot is free, no prefill is in flight, and the queue
   is non-empty, the head request starts prefilling;
2. chunked prefill — at most ONE prompt chunk runs per iteration, so a
   long prompt never stalls running requests' TPOT for more than a chunk
   (Dynamic SplitFuse's interleave-heterogeneous-work principle, applied
   as program interleaving instead of a fused megabatch — static shapes
   stay static);
3. decode — every occupied slot advances one token;
4. retirement — rows that hit eos or their max_new free their slot
   immediately; the slot is reusable the very next iteration.

Chunk plans are shape-bucketed: every chunk is either exactly
``prefill_chunk`` tokens or a power-of-two bucket below it, so the steady
state reuses a compiled-program set bounded by the bucket count — no
matter what prompt lengths traffic brings (docs/SERVING.md).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

import numpy as np

from ..observability import spans as _spans
from ..observability.export import hop_trace
from ..observability.tracing import ServingStats
from ..resilience.guards import QueueFullError, RequestStatus

_MIN_BUCKET = 8   # smallest residual-chunk program; below this, right-pad


@dataclasses.dataclass
class ChunkPlan:
    """One prefill chunk: run ``ids`` (already bucket-sized) with the cache
    length rewound to ``start``; ``final`` chunks also sample the first
    token from position ``last_index`` and set the cache to ``true_len``.

    Two bucketing tricks keep shapes bounded WITHOUT corrupting the cache:
    - overlap: a residual of r tokens re-runs the last ``size`` >= r prompt
      tokens (recomputing a suffix writes bit-identical KV, so rewinding
      ``start`` is free) — used whenever the prompt is long enough;
    - right-pad: short prompts pad up to the bucket; the pad's garbage KV
      lands at positions >= ``true_len``, which the attention mask already
      ignores and the first decode steps progressively overwrite.
    """

    start: int                    # cache position this chunk writes from
    ids: np.ndarray               # (size,) int32 token ids (padded if needed)
    final: bool = False
    last_index: int = 0           # position of the last REAL token in ids
    true_len: int = 0             # prompt length the cache ends at

    @property
    def size(self) -> int:
        return len(self.ids)


def _pow2_ceil(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def plan_chunks(prompt: np.ndarray, chunk: int, skip: int = 0) -> list:
    """Split one prompt into bucket-shaped prefill chunks.

    Full ``chunk``-size chunks cover the head of the prompt; the residual
    runs as the smallest power-of-two bucket >= max(residual, 8), via
    overlap when the prompt affords it, else right-padding.

    ``skip`` (prefix sharing, serving/pages.py) drops the first ``skip``
    tokens from the plan: their KV is hydrated from shared pool pages, so
    only the suffix is recomputed. Chunk shapes stay in the same bucket
    set regardless of ``skip`` — sharing never compiles a new program —
    and the final overlap bucket may rewind INTO the hydrated region,
    rewriting bit-identical KV (the chunked==whole prefill oracle)."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    P = len(prompt)
    if P < 1:
        raise ValueError("empty prompt")
    if not 0 <= skip < P:
        raise ValueError(f"skip={skip} outside [0, {P})")
    Q = P - skip                         # tokens actually recomputed
    k = (Q - 1) // chunk                 # full chunks before the residual
    r = Q - k * chunk                    # residual, in (0, chunk]
    plans = [ChunkPlan(start=skip + i * chunk,
                       ids=prompt[skip + i * chunk:skip + (i + 1) * chunk])
             for i in range(k)]
    b = max(_MIN_BUCKET, _pow2_ceil(r))
    if P >= b:        # overlap: recompute the last b prompt tokens
        plans.append(ChunkPlan(start=P - b, ids=prompt[P - b:], final=True,
                               last_index=b - 1, true_len=P))
    else:             # short prompt: right-pad to the bucket
        ids = np.concatenate([prompt, np.zeros(b - P, np.int32)])
        plans.append(ChunkPlan(start=0, ids=ids, final=True,
                               last_index=P - 1, true_len=P))
    return plans


@dataclasses.dataclass
class Request:
    """One served request, host-side.

    ``status`` is the terminal outcome (:class:`RequestStatus`) — callers
    branch on it instead of inferring from token shapes. ``deadline_ttft``
    / ``deadline_total`` are ABSOLUTE times on the stats clock (submit
    time + the configured budgets), None when no deadline applies."""

    rid: int
    prompt: np.ndarray
    max_new: int
    seed: int
    # failover visibility (serving/fleet.py): how many times this request
    # was REQUEUED onto another replica after its original replica was
    # lost. 0 on the single-engine path; surfaced in inflight_table and
    # the request-log record so failover is never silent.
    attempts: int = 0
    # fleet session affinity key (None outside the fleet router)
    session_id: "object | None" = None
    # cost-attribution dimension (observability/tenantscope.py): which
    # tenant this request bills to. "default" when the caller never set
    # one — the inert value every pre-tenant record upgrades to.
    tenant_id: str = "default"
    submit_t: float = 0.0
    admit_t: Optional[float] = None       # left the queue (prefill starts)
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    slot: int = -1
    tokens: list = dataclasses.field(default_factory=list)
    status: RequestStatus = RequestStatus.OK
    error: str = ""
    deadline_ttft: Optional[float] = None
    deadline_total: Optional[float] = None
    # distributed-trace hop stamps (observability/export.py hop_trace):
    # requeue_t — when a failover pulled this request off its dead
    # replica (kill → re-admission is Serve/requeue_delay_s); export_t —
    # when the prefill replica finished exporting its pages to host;
    # import_t0/import_t1 — the disaggregated decode-side import window.
    # All None on the plain single-engine path.
    requeue_t: Optional[float] = None
    export_t: Optional[float] = None
    import_t0: Optional[float] = None
    import_t1: Optional[float] = None
    # paged-KV admission plan (serving/pages.py PageAllocation): the
    # slot's page-table row, shared-prefix skip, and hydrate plan. None
    # on the contiguous path.
    page_alloc: Optional[object] = None

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def finished(self) -> bool:
        return self.finish_t is not None

    @property
    def ok(self) -> bool:
        return self.finished and self.status is RequestStatus.OK


class Scheduler:
    """Queue + slot bookkeeping; all decisions, no device code.

    The engine asks ``pop_next()`` for the next request to prefill, then
    ``place()``s it into a slot (or ``complete_at_prefill()`` if its first
    token already finished it), and reports every decode step through
    ``on_step`` — which appends tokens, retires rows at eos / max_new, and
    frees their slots. FIFO admission; retirement order is whatever the
    tokens dictate.
    """

    def __init__(self, slots: int, max_len: int, prefill_chunk: int,
                 max_queue: int = 0, eos_token_id: Optional[int] = None,
                 stats: Optional[ServingStats] = None,
                 ttft_deadline_s: float = 0.0,
                 total_deadline_s: float = 0.0,
                 spans: "Optional[_spans.SpanRecorder]" = None,
                 pages=None, rid_source=None):
        self.slots = slots
        self.max_len = max_len
        self.prefill_chunk = prefill_chunk
        self.max_queue = max_queue
        self.eos_token_id = eos_token_id
        # paged-KV pool (serving/pages.py PagePool): admission consults
        # the prefix tree — and, when a host tier is attached
        # (serving/hostkv.py), the pinned-host cold store right after
        # it — and takes page refs; every terminal path releases them.
        # A restored admission's plan() shrinks exactly like a tree
        # hit's (skip covers the restored blocks); the engine scatters
        # the host tiles before the first chunk runs. None = contiguous
        # slot cache, nothing paged.
        self.pages = pages
        self._defer_key = None   # (rid, pool generation) of a failed admit
        self.stats = stats if stats is not None else ServingStats()
        self.ttft_deadline_s = float(ttft_deadline_s)
        self.total_deadline_s = float(total_deadline_s)
        # lifecycle span emission (observability/spans.py): every edge the
        # scheduler already stamps becomes a typed event. None (default) =
        # zero extra work beyond these `is not None` checks.
        self.spans = spans
        self.queue: deque[Request] = deque()
        self.free: list[int] = list(range(slots))
        self.running: dict[int, Request] = {}
        # rid allocation seam: the fleet router shares ONE counter across
        # every replica's scheduler so a request id names a request
        # fleet-wide (pop_result routes by rid, requeue keeps the id).
        # None (default) = this scheduler owns its own namespace.
        self.rid_source = rid_source
        self._next_rid = 0

    # -------------------------------------------------------------- intake
    def submit(self, prompt, max_new: int, seed: int = 0,
               ttft_deadline_s: Optional[float] = None,
               total_deadline_s: Optional[float] = None,
               session_id=None, tenant_id: Optional[str] = None) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if len(prompt) + max_new > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new ({max_new}) exceeds the "
                f"slot capacity max_len={self.max_len} — raise "
                f"serving.max_len or trim the request")
        if self.max_queue and len(self.queue) >= self.max_queue:
            self.stats.on_shed(len(self.queue))
            raise QueueFullError(
                f"serving queue full ({self.max_queue}); apply backpressure",
                queue_depth=len(self.queue), max_queue=self.max_queue)
        if self.pages is not None:
            # typed PagePoolExhausted (status SHED) when the pool could
            # NEVER cover this request's worst-case pages — a transient
            # shortage instead defers at the queue head (pop_next)
            try:
                self.pages.check_submit(len(prompt), int(max_new))
            except QueueFullError:
                self.stats.on_shed(len(self.queue))
                raise
        if self.rid_source is not None:
            rid = int(self.rid_source())
        else:
            rid = self._next_rid
            self._next_rid += 1
        req = Request(rid=rid, prompt=prompt, max_new=int(max_new),
                      seed=int(seed), session_id=session_id,
                      tenant_id="default" if tenant_id is None
                      else str(tenant_id))
        self.queue.append(req)
        req.submit_t = self.stats.on_submit(len(self.queue))
        ttft = self.ttft_deadline_s if ttft_deadline_s is None \
            else float(ttft_deadline_s)
        total = self.total_deadline_s if total_deadline_s is None \
            else float(total_deadline_s)
        if ttft > 0:
            req.deadline_ttft = req.submit_t + ttft
        if total > 0:
            req.deadline_total = req.submit_t + total
        return req

    # ----------------------------------------------------------- admission
    def pop_next(self) -> Optional[Request]:
        """Head-of-queue request to start prefilling, if a slot is free —
        and, on the paged path, if the page pool can cover its worst-case
        pages right now (admission consults the prefix tree; a transient
        shortage leaves the head queued until a retirement frees pages,
        so a mid-decode pool OOM is impossible by construction). The
        engine guarantees at most one prefill in flight."""
        if not self.queue or not self.free:
            return None
        if self.pages is not None:
            head = self.queue[0]
            # retry gate: a failed try_admit re-runs the full tree match
            # + eviction walk, so only retry once admission prospects
            # changed (a release freed pages / new prefixes registered)
            key = (head.rid, self.pages.generation)
            if key == self._defer_key:
                return None
            alloc = self.pages.try_admit(head.prompt, head.max_new,
                                         head.rid)
            if alloc is None:
                self._defer_key = key
                return None          # pool transiently full: FIFO holds
            self._defer_key = None
            head.page_alloc = alloc
        req = self.queue.popleft()
        admit_t = self.stats.on_admit(len(self.queue), submit_t=req.submit_t)
        req.admit_t = admit_t
        if req.requeue_t is not None:
            # failover attribution: kill → re-admission, its OWN series
            # so TTFT and requeue delay stay separable in the logs
            self.stats.on_requeue_delay(admit_t - req.requeue_t)
        if self.spans is not None:
            # the queue-wait span: submitted → picked for prefill. A
            # requeued ATTEMPT's span starts at the requeue (its first
            # attempt already burned the wait from submit_t) and carries
            # the attempt index, so per-attempt timings never conflate.
            self.spans.emit(_spans.QUEUED,
                            req.submit_t if req.requeue_t is None
                            else req.requeue_t,
                            admit_t, rid=req.rid,
                            **self._attempt_meta(req))
        return req

    @staticmethod
    def _attempt_meta(req: Request) -> dict:
        """Span meta labeling which failover attempt an event belongs
        to — empty on the never-requeued path, so single-engine span
        streams are byte-identical to before the fleet existed."""
        return {"attempt": req.attempts} if req.attempts else {}

    def plan(self, req: Request) -> list:
        skip = req.page_alloc.skip if req.page_alloc is not None else 0
        return plan_chunks(req.prompt, self.prefill_chunk, skip=skip)

    def _release_pages(self, req: Request) -> None:
        """Every terminal path funnels here: drop the request's page
        refcounts (shared pages survive for future sharing via their
        tree reference; private pages free immediately)."""
        if self.pages is not None and req.page_alloc is not None:
            self.pages.release(req.rid)

    def place(self, req: Request, first_tok: int) -> int:
        """Prefill finished: record the first token, occupy a slot."""
        req.first_token_t = self.stats.on_first_token(req.submit_t)
        req.tokens.append(int(first_tok))
        slot = self.free.pop(0)
        req.slot = slot
        self.running[slot] = req
        if self.spans is not None:
            self.spans.emit(_spans.PLACED, req.first_token_t, rid=req.rid,
                            slot=slot, **self._attempt_meta(req))
        return slot

    def adopt(self, req: Request) -> int:
        """Seat an ALREADY-prefilled request into a free slot without
        re-recording its first token (disaggregated serving: the prefill
        replica stamped ``first_token_t`` and appended the first token;
        this decode-side scheduler only takes over the residency). The
        caller guarantees a free slot exists."""
        slot = self.free.pop(0)
        req.slot = slot
        self.running[slot] = req
        if self.spans is not None:
            self.spans.emit(_spans.PLACED, self.stats.clock(), rid=req.rid,
                            slot=slot, imported=True,
                            **self._attempt_meta(req))
        return slot

    def requeue(self, req: Request) -> Request:
        """Failover intake (serving/fleet.py): re-queue a request whose
        replica was lost. The typed ``REQUEUED`` transition + ``attempts``
        bump make the move visible; everything transient (tokens, slot,
        first-token stamp, page plan) resets so the request re-runs from
        prefill on THIS scheduler — per-request RNG folds from the seed,
        so the rerun's bits match a fresh submission. ``submit_t`` and the
        ABSOLUTE deadlines are preserved: failover does not grant a
        request more wall time than its caller asked for."""
        if len(req.prompt) + req.max_new > self.max_len:
            raise ValueError(
                f"requeued request {req.rid} (prompt {len(req.prompt)} + "
                f"max_new {req.max_new}) exceeds max_len={self.max_len}")
        req.status = RequestStatus.REQUEUED
        req.attempts += 1
        req.tokens = []
        req.slot = -1
        req.first_token_t = None
        req.finish_t = None
        req.admit_t = None
        req.page_alloc = None
        req.error = ""
        # per-attempt trace stamps restart with the attempt: the NEW
        # requeue_t anchors Serve/requeue_delay_s (kill → re-admission)
        # and the surviving attempt's hop decomposition; a stale import
        # window from the dead replica must not leak into it
        req.requeue_t = self.stats.clock()
        req.export_t = None
        req.import_t0 = None
        req.import_t1 = None
        # oldest-first at the head: a requeued request already spent its
        # queue wait once; survivors' fresher submissions queue behind it
        self.queue.appendleft(req)
        self.stats.on_requeue(len(self.queue))
        if self.spans is not None:
            self.spans.emit(_spans.RETIRED, req.requeue_t, rid=req.rid,
                            slot=None, status=req.status.value,
                            tokens=0, attempt=req.attempts)
        return req

    def take_live(self) -> list:
        """Pull EVERY live request out of this scheduler (queue + running
        slots), oldest submission first — the replica-loss path: the
        fleet requeues them onto survivors. Slots free and the queue
        empties; page refs are NOT released (the pool dies with the
        replica)."""
        live = list(self.queue) + list(self.running.values())
        self.queue.clear()
        self.running.clear()
        self.free = list(range(self.slots))
        return sorted(live, key=lambda r: (r.submit_t, r.rid))

    def complete_at_prefill(self, req: Request, first_tok: int) -> Request:
        """max_new == 1, or the first token was eos: done without ever
        occupying a slot."""
        req.first_token_t = self.stats.on_first_token(req.submit_t)
        req.tokens.append(int(first_tok))
        req.finish_t = self.stats.on_retire(len(req.tokens),
                                            req.first_token_t)
        self._release_pages(req)
        self._span_retire(req)
        return req

    def _span_retire(self, req: Request) -> None:
        """Terminal span pair: the decode-residency span (first token →
        retirement, when the request ever held a slot) plus the typed
        RETIRED instant every terminal path emits."""
        if self.spans is None:
            return
        if req.slot >= 0 and req.first_token_t is not None \
                and req.finish_t is not None:
            self.spans.emit(_spans.DECODE_RESIDENCY,
                            req.import_t1 if req.import_t1 is not None
                            else req.first_token_t,
                            req.finish_t, rid=req.rid, slot=req.slot,
                            tokens=len(req.tokens),
                            **self._attempt_meta(req))
        self.spans.emit(_spans.RETIRED,
                        req.finish_t if req.finish_t is not None
                        else req.submit_t,
                        rid=req.rid,
                        slot=req.slot if req.slot >= 0 else None,
                        status=req.status.value, tokens=len(req.tokens),
                        **self._attempt_meta(req))

    # -------------------------------------------------------------- decode
    def on_step(self, toks: np.ndarray, dones: np.ndarray) -> list:
        """Account one slot decode step: per-slot next tokens + done flags
        (device read-back). Returns the requests retired this step."""
        finished = []
        for slot in sorted(self.running):
            req = self.running[slot]
            req.tokens.append(int(toks[slot]))
            if bool(dones[slot]) or len(req.tokens) >= req.max_new:
                req.status = RequestStatus.OK
                req.finish_t = self.stats.on_retire(len(req.tokens),
                                                    req.first_token_t)
                del self.running[slot]
                self.free.append(slot)
                self._release_pages(req)
                finished.append(req)
                self._span_retire(req)
        return finished

    def on_spec_step(self, emitted: dict) -> list:
        """Account one speculative verify step: ``emitted`` maps slot →
        the tokens that step committed for that slot (the carried token's
        verification plus every accepted draft — at least one token,
        already truncated at the first eos by the engine's host-side
        acceptance). Retirement is the same predicate as :meth:`on_step`
        applied to the LAST committed token, so a request retires on the
        exact step the plain lane would have reached that token.

        Paged retirements first roll the page table back to the final
        committed KV extent (``prompt + tokens - 1``: the last emitted
        token is the next step's carry, its KV never written) — the
        rejected drafts' garbage tail drops its pages via
        :meth:`~.pages.PagePool.truncate` before the ordinary release,
        so rollback-then-release refcounts stay exact."""
        finished = []
        for slot in sorted(emitted):
            req = self.running.get(slot)
            toks = emitted[slot]
            if req is None or not toks:
                continue
            req.tokens.extend(int(t) for t in toks)
            hit_eos = self.eos_token_id is not None \
                and int(req.tokens[-1]) == self.eos_token_id
            if hit_eos or len(req.tokens) >= req.max_new:
                req.status = RequestStatus.OK
                req.finish_t = self.stats.on_retire(len(req.tokens),
                                                    req.first_token_t)
                del self.running[slot]
                self.free.append(slot)
                if self.pages is not None:
                    self.pages.truncate(
                        req.rid, len(req.prompt) + len(req.tokens) - 1)
                self._release_pages(req)
                finished.append(req)
                self._span_retire(req)
        return finished

    # ------------------------------------------------------------- guards
    def abort(self, req: Request, status: RequestStatus,
              error: str = "") -> Request:
        """Terminate ``req`` with a non-OK status: free its slot if it
        holds one, record the typed outcome, count it in Serve/*. The
        engine uses this for requests it holds itself (the in-flight
        prefill); queue/slot residents go through :meth:`cancel` /
        :meth:`expire_deadlines`."""
        if req.slot >= 0 and req.slot in self.running \
                and self.running[req.slot] is req:
            del self.running[req.slot]
            self.free.append(req.slot)
        req.status = status
        req.error = error
        req.finish_t = self.stats.on_abort(status)
        self._release_pages(req)
        self._span_retire(req)
        return req

    def cancel(self, rid: int) -> Optional[Request]:
        """Cancel a queued or running request by id; returns it (status
        ``CANCELLED``) or None if this scheduler doesn't hold it (already
        finished, unknown, or held by the engine's prefill lane)."""
        for req in self.queue:
            if req.rid == rid:
                self.queue.remove(req)
                self.stats.registry.gauge("Serve/queue_depth").set(
                    len(self.queue))
                return self.abort(req, RequestStatus.CANCELLED,
                                  "cancelled while queued")
        for slot, req in list(self.running.items()):
            if req.rid == rid:
                return self.abort(req, RequestStatus.CANCELLED,
                                  "cancelled while decoding")
        return None

    def expire_deadlines(self, now: float) -> list:
        """Retire every request whose deadline passed: queued requests
        against BOTH deadlines (a request that cannot make TTFT from the
        queue is dead weight), running requests against the total-wall
        one (their first token already landed). Returns the expired
        requests, status ``TIMEOUT``."""
        expired = []
        for req in [r for r in self.queue
                    if (r.deadline_ttft is not None and now >= r.deadline_ttft)
                    or (r.deadline_total is not None
                        and now >= r.deadline_total)]:
            self.queue.remove(req)
            which = "ttft" if (req.deadline_ttft is not None
                              and now >= req.deadline_ttft) else "total"
            expired.append(self.abort(req, RequestStatus.TIMEOUT,
                                      f"{which} deadline expired in queue"))
        if expired:
            self.stats.registry.gauge("Serve/queue_depth").set(len(self.queue))
        for slot, req in list(self.running.items()):
            if req.deadline_total is not None and now >= req.deadline_total:
                expired.append(self.abort(req, RequestStatus.TIMEOUT,
                                          "total deadline expired"))
        return expired

    def retire_nonfinite(self, bad_slots) -> list:
        """The per-row logit guard tripped: retire exactly the poisoned
        slots' requests with ``NONFINITE``. Called BEFORE ``on_step``
        accounting, so the poisoned row's garbage token of this step is
        never appended; every other slot's bookkeeping is untouched."""
        out = []
        for slot in bad_slots:
            req = self.running.get(int(slot))
            if req is not None:
                out.append(self.abort(
                    req, RequestStatus.NONFINITE,
                    f"non-finite logits in slot {int(slot)}"))
        return out

    # ------------------------------------------------------------- readout
    def inflight_table(self, prefill: Optional[Request] = None) -> list:
        """Live in-flight request table for the telemetry plane's
        ``GET /requests``: the engine's prefill-lane resident (passed
        in — the scheduler doesn't hold it), every decoding slot, then
        the queue in FIFO order. Pure host bookkeeping, copied
        defensively so the HTTP thread never iterates a mutating
        container."""

        def row(req: Request, state: str) -> dict:
            return {
                "rid": req.rid, "state": state,
                "slot": req.slot if req.slot >= 0 else None,
                "prompt_len": req.prompt_len, "max_new": req.max_new,
                "tokens": len(req.tokens), "submit_t": req.submit_t,
                "admit_t": req.admit_t,
                "deadline_ttft": req.deadline_ttft,
                "deadline_total": req.deadline_total,
                # failover visibility: a requeued request shows its typed
                # status and move count while it waits again
                "status": req.status.value,
                "attempts": req.attempts,
                "tenant_id": req.tenant_id,
                # live hop decomposition: hops the request has completed
                # so far (the rest null) — /requests shows where an
                # in-flight request's time is going
                "trace": hop_trace(req),
                # tiered-KV visibility: how much of this request's
                # prefix came from the pool/host tier instead of
                # recompute (0 without a page allocation)
                "skip_tokens": (req.page_alloc.skip
                                if req.page_alloc is not None else 0),
                "restored_pages": (getattr(req.page_alloc, "restored", 0)
                                   if req.page_alloc is not None else 0),
            }

        rows = []
        if prefill is not None:
            rows.append(row(prefill, "prefill"))
        running = dict(self.running)
        for slot in sorted(running):
            rows.append(row(running[slot], "decoding"))
        for req in list(self.queue):
            rows.append(row(req, "queued"))
        return rows

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def occupancy(self) -> int:
        return len(self.running)

    @property
    def idle(self) -> bool:
        return not self.queue and not self.running
