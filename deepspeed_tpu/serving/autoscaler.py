"""Elastic fleet autoscaler: the actuation half of the scaling loop.

PR 17's loadscope observatory (``observability/loadscope.py``) landed
the *estimation* half — :meth:`~.fleet.FleetEngine.scaling_report`
measures arrival rate, per-phase utilization ρ, SLO time-to-violation,
and scores the add/remove/rebalance what-ifs. This module is the
*control loop* the ROADMAP carved out for it: :class:`Autoscaler`
CONSUMES that report verbatim (it never re-derives an estimate — every
actuation's decision record embeds the ``scaling_report()`` inputs it
fired on) and decides WHEN a score is trustworthy enough to act on,
under explicit robustness guards. Reference analog: DeepSpeed's
elasticity package, rebuilt as a serving-fleet control plane with
ZeRO-Infinity's degrade-gracefully discipline applied to scale events.

The guards, each of which exists because the naive loop fails without
it:

- **per-direction hysteresis** — a score must stay armed for
  ``up_ticks`` / ``down_ticks`` consecutive evaluations before the
  loop actuates, so one bursty window cannot trigger a scale event;
- **cooldown windows** — after any actuation the SAME direction holds
  for ``cooldown_up_s`` / ``cooldown_down_s`` (capacity changes take
  a window to show up in ρ; acting again before the estimator
  re-converges double-corrects);
- **a flap budget** — direction reversals (add after remove or vice
  versa) inside ``flap_window_s`` are counted; at ``flap_budget`` the
  loop FREEZES itself and alarms instead of oscillating (an
  oscillating trace must cost at most ``flap_budget`` reversals — the
  ``bench_autoscale.py`` flap-bait oracle);
- **score-trust gating** — a what-if that self-demoted to 0 with a
  stated reason, an unmeasured ρ (null report / empty what-ifs), or a
  ``saturated`` forecast (the queue-wait prediction is null past the
  knee) NEVER actuates: the loop records an alarm decision and holds.
  Saturation in particular means the estimator can no longer price the
  move — paging a human beats acting on an unpriceable forecast;
- **drain-before-remove** — scale-down drains the victim first
  (:meth:`~.fleet.FleetEngine.begin_drain_replica`: intake closes,
  backlog finishes, pending handoffs re-route to its siblings) and
  removes it only once idle, so a clean scale-down requeues NOTHING.
  The drain is bounded by ``drain_deadline_s`` — past the deadline the
  victim is removed anyway and its stragglers requeue onto survivors
  (zero loss either way); and it aborts on load reversal: if the
  scale-up signal arms while a victim drains, ``end_drain_replica``
  reopens intake and the replica is NOT removed;
- **an incident cooldown latch** — a chaos/replica kill
  (:meth:`~.fleet.FleetEngine.kill_replica` calls
  :meth:`Autoscaler.on_incident`) latches scale-down and rebalance off
  for ``incident_cooldown_s``: failover requeues depress the measured
  arrival exactly like a real lull, and a loop without the latch reads
  its own incident as "remove a replica";
- **manual freeze/pin** — ``POST /autoscale {"freeze": true}``
  (token-gated, for deploys) stops all actuation while evaluations and
  alarms continue; ``{"pin": [names]}`` shields specific replicas from
  ever being chosen as drain victims.

Every evaluation that matters produces a typed
:class:`AutoscaleDecision` (inputs snapshot, rule fired, action,
outcome) in a bounded audit ring — the ring feeds ``GET /autoscale``,
``Fleet/autoscale_*`` metrics, the doctor's ``[autoscale]`` section,
and the fleet's incident dumps (``fleet/autoscale_audit.jsonl``).

Inert by default: ``serving.autoscale=None`` builds NOTHING — the
fleet pays one ``is not None`` per step, zero threads, zero new
compiled programs, zero syncs (the ``bench_autoscale.py --smoke``
compile freeze is the oracle). The loop has no thread of its own even
when on: it piggybacks on :meth:`~.fleet.FleetEngine.step` at
``tick_s`` cadence on the fleet's injectable clock, so fake-clock
chaos benches drive it deterministically.
"""

from __future__ import annotations

import dataclasses
import json
from collections import deque
from typing import Optional

__all__ = ["AutoscaleConfig", "AutoscaleDecision", "Autoscaler"]

# decision outcomes (the audit ring's closed vocabulary)
ACTUATED = "actuated"
DRAIN_STARTED = "drain_started"
DRAIN_ABORTED = "drain_aborted"
REMOVED = "removed"
REMOVED_AT_DEADLINE = "removed_at_deadline"
ALARM = "alarm"
SUPPRESSED = "suppressed"


@dataclasses.dataclass
class AutoscaleConfig:
    """``serving.autoscale`` — the control-loop knobs. All windows are
    in the fleet clock's seconds (fake seconds under a test clock).
    Sizing guidance lives in docs/OPERATIONS.md ("running the
    autoscaler"): thresholds come from the what-if score distribution
    in ``LOADSCOPE_BENCH.json``, cooldowns from the loadscope window,
    the flap budget from how often you can stomach a reversal."""

    enabled: bool = True
    # evaluation cadence: scaling_report() is consulted at most once
    # per tick_s (the drain progress check runs every step — it is one
    # idle probe, the report is a registry walk)
    tick_s: float = 5.0
    # score thresholds (0-100, against loadscope's what-if scores):
    # the signal "arms" when the action's score reaches its threshold
    add_score_min: float = 60.0
    remove_score_min: float = 60.0
    rebalance_score_min: float = 60.0
    # per-direction hysteresis: consecutive armed evaluations required
    # before actuating (scale-down is slower by default — adding
    # capacity late costs SLO, removing it early costs SLO twice)
    up_ticks: int = 2
    down_ticks: int = 3
    # post-actuation cooldowns per direction
    cooldown_up_s: float = 30.0
    cooldown_down_s: float = 60.0
    # direction reversals tolerated inside flap_window_s before the
    # loop freezes itself (0 = any reversal freezes)
    flap_budget: int = 2
    flap_window_s: float = 600.0
    # bounded drain: a victim still busy past the deadline is removed
    # anyway (its stragglers requeue — zero loss, bounded latency)
    drain_deadline_s: float = 60.0
    # scale-down/rebalance latch after a replica kill or incident
    incident_cooldown_s: float = 120.0
    # fleet size rails (min_replicas also floors rebalance donors)
    min_replicas: int = 1
    max_replicas: int = 8
    # decision audit ring capacity
    audit_ring: int = 256

    def __post_init__(self):
        if self.tick_s <= 0:
            raise ValueError(f"tick_s must be > 0, got {self.tick_s}")
        for knob in ("add_score_min", "remove_score_min",
                     "rebalance_score_min"):
            v = getattr(self, knob)
            if not 0.0 <= v <= 100.0:
                raise ValueError(f"{knob} must be in [0, 100], got {v}")
        for knob in ("up_ticks", "down_ticks"):
            if getattr(self, knob) < 1:
                raise ValueError(f"{knob} must be >= 1, "
                                 f"got {getattr(self, knob)}")
        for knob in ("cooldown_up_s", "cooldown_down_s",
                     "incident_cooldown_s"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0, "
                                 f"got {getattr(self, knob)}")
        if self.flap_budget < 0:
            raise ValueError(f"flap_budget must be >= 0, "
                             f"got {self.flap_budget}")
        if self.flap_window_s <= 0:
            raise ValueError(f"flap_window_s must be > 0, "
                             f"got {self.flap_window_s}")
        if self.drain_deadline_s <= 0:
            raise ValueError(f"drain_deadline_s must be > 0, "
                             f"got {self.drain_deadline_s}")
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, "
                             f"got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas={self.max_replicas} < "
                f"min_replicas={self.min_replicas}")
        if self.audit_ring < 1:
            raise ValueError(f"audit_ring must be >= 1, "
                             f"got {self.audit_ring}")

    @classmethod
    def from_any(cls, cfg: "AutoscaleConfig | dict | None") \
            -> "Optional[AutoscaleConfig]":
        if cfg is None or isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown autoscale config keys: {sorted(unknown)}")
        return cls(**cfg)


@dataclasses.dataclass
class AutoscaleDecision:
    """One control-loop decision: what the loop saw (``inputs`` is the
    ``scaling_report()`` excerpt it fired on — fleet aggregates plus
    the relevant what-if entry, verbatim), which rule fired, what it
    did about it, and how that turned out. The audit ring holds these
    so a bad scale event is explicable after the fact."""

    seq: int
    t: float
    rule: str                    # which guard/signal produced this
    action: str                  # add_replica / remove_replica / ...
    outcome: str                 # actuated / drain_started / alarm / ...
    target: str = ""             # replica name, when one is involved
    reason: str = ""             # human-readable why
    inputs: dict = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _report_inputs(report: "Optional[dict]",
                   what_if: "Optional[dict]" = None) -> dict:
    """The inputs snapshot an actuation must trace to: the report's
    fleet aggregate block and the scoring entry, copied verbatim (no
    re-derived numbers — the acceptance contract)."""
    if report is None:
        return {"fleet": None, "what_if": what_if}
    return {"fleet": dict(report.get("fleet") or {}),
            "what_if": dict(what_if) if what_if is not None else None}


class Autoscaler:
    """The hysteresis-guarded actuation loop over one
    :class:`~.fleet.FleetEngine`. Built by the fleet when
    ``serving.autoscale`` is configured and enabled; never constructs
    threads — :meth:`on_step` is called from ``FleetEngine.step()``."""

    def __init__(self, fleet, cfg: "AutoscaleConfig | dict | None"):
        from .fleet import ROLE_DECODE, ROLE_PREFILL, ROLE_SERVE

        self.fleet = fleet
        self.cfg = AutoscaleConfig.from_any(cfg) or AutoscaleConfig()
        self._roles = (ROLE_SERVE, ROLE_PREFILL, ROLE_DECODE)
        self._clock = fleet._clock
        self.registry = fleet.registry
        self.audit: deque = deque(maxlen=self.cfg.audit_ring)
        self._seq = 0
        self.evals = 0
        self._last_eval: Optional[float] = None
        # per-direction streaks (consecutive armed evaluations)
        self._streak = {"add": 0, "remove": 0, "rebalance": 0}
        # cooldown horizons per direction ("up" = add, "down" = remove
        # AND rebalance — both take capacity out of a role)
        self._cooldown_until = {"up": float("-inf"),
                                "down": float("-inf")}
        # recent direction reversals (timestamps) inside flap_window_s
        self._flaps: deque = deque()
        self._last_direction: Optional[str] = None
        self._last_actuation_t: Optional[float] = None
        # drain-before-remove in flight: (victim, deadline, add_role)
        # — add_role is the role to add after removal (rebalance), or
        # "" for a plain scale-down
        self._drain: Optional[tuple] = None
        # incident latch horizon (on_incident pushes it forward)
        self._incident_until = float("-inf")
        self.incidents = 0
        # manual overrides (POST /autoscale)
        self._frozen = False
        self._frozen_since: Optional[float] = None
        self._frozen_by = ""           # "manual" | "flap_budget"
        self._pinned: set = set()
        # dedup key for alarm/suppress decisions so a held state does
        # not flood the ring once per tick
        self._last_quiet_key: Optional[tuple] = None
        self._export_gauges()

    # ------------------------------------------------------------- audit
    def _record(self, rule: str, action: str, outcome: str,
                target: str = "", reason: str = "",
                inputs: "Optional[dict]" = None,
                dedup: bool = False) -> AutoscaleDecision:
        """Append one decision; ``dedup=True`` (alarms/suppressions)
        collapses consecutive repeats of the same (rule, action,
        outcome) so a held guard writes one entry, not one per tick."""
        key = (rule, action, outcome, target)
        if dedup and key == self._last_quiet_key:
            return None
        self._last_quiet_key = key if dedup else None
        self._seq += 1
        d = AutoscaleDecision(
            seq=self._seq, t=self._clock(), rule=rule, action=action,
            outcome=outcome, target=target, reason=reason,
            inputs=inputs if inputs is not None else {})
        self.audit.append(d)
        r = self.registry
        r.counter("Fleet/autoscale_decisions").inc()
        if outcome == ALARM:
            r.counter("Fleet/autoscale_alarms").inc()
        elif outcome == SUPPRESSED:
            r.counter("Fleet/autoscale_suppressed").inc()
        return d

    def audit_entries(self) -> list:
        """The decision ring, oldest first, as plain dicts."""
        return [d.as_dict() for d in self.audit]

    def audit_jsonl(self) -> str:
        return "\n".join(json.dumps(d.as_dict(), separators=(",", ":"),
                                    default=str)
                         for d in self.audit) + "\n"

    # ----------------------------------------------------------- metrics
    def _flap_budget_remaining(self, now: float) -> int:
        while self._flaps and now - self._flaps[0] > self.cfg.flap_window_s:
            self._flaps.popleft()
        return max(0, self.cfg.flap_budget - len(self._flaps))

    def _export_gauges(self) -> None:
        now = self._clock()
        frozen_stale = (now - self._frozen_since
                        if self._frozen and self._frozen_since is not None
                        else 0.0)
        self.registry.set_gauges({
            "Fleet/autoscale_enabled": 1.0,
            "Fleet/autoscale_frozen": 1.0 if self._frozen else 0.0,
            "Fleet/autoscale_frozen_stale_s": float(frozen_stale),
            "Fleet/autoscale_flap_budget_remaining":
                float(self._flap_budget_remaining(now)),
            "Fleet/autoscale_draining": 1.0 if self._drain else 0.0,
            "Fleet/autoscale_incident_latched":
                1.0 if now < self._incident_until else 0.0,
        })

    # ------------------------------------------------------------ intake
    def on_incident(self, kind: str, replica: str = "") -> None:
        """A replica kill / chaos fault just happened: latch scale-down
        and rebalance for ``incident_cooldown_s`` so the failover's
        arrival dip is never misread as a remove signal. An in-flight
        drain on the KILLED victim is cleared (nothing left to remove);
        a drain on another replica aborts — post-incident capacity
        math is stale."""
        now = self._clock()
        self.incidents += 1
        self._incident_until = now + self.cfg.incident_cooldown_s
        self.registry.counter("Fleet/autoscale_incidents").inc()
        if self._drain is not None:
            victim, _deadline, _add_role = self._drain
            self._drain = None
            if victim != replica and victim in self.fleet.replicas:
                self.fleet.end_drain_replica(victim)
                self.registry.counter("Fleet/autoscale_drain_aborts").inc()
            self._record("incident", "end_drain", DRAIN_ABORTED,
                         target=victim,
                         reason=f"{kind} on {replica or '?'} during "
                                "drain — post-incident capacity is "
                                "stale; victim keeps serving")
        self._record("incident", "hold", ALARM, target=replica,
                     reason=f"{kind}: scale-down latched for "
                            f"{self.cfg.incident_cooldown_s:g}s",
                     dedup=False)
        self._export_gauges()

    # ----------------------------------------------------------- control
    def freeze(self, on: bool = True, by: str = "manual") -> None:
        if on and not self._frozen:
            self._frozen = True
            self._frozen_since = self._clock()
            self._frozen_by = by
            self._record("freeze", "hold", SUPPRESSED,
                         reason=f"frozen by {by}")
        elif not on and self._frozen:
            self._frozen = False
            self._frozen_since = None
            self._frozen_by = ""
            self._last_quiet_key = None
            self._record("unfreeze", "resume", ACTUATED,
                         reason="actuation re-enabled")
        self._export_gauges()

    def control(self, body: dict) -> dict:
        """The ``POST /autoscale`` hook: ``{"freeze": bool}`` and/or
        ``{"pin": [names]}`` / ``{"unpin": [names]}``. Unknown keys
        raise (→ 400); returns the post-change status."""
        if not isinstance(body, dict):
            raise ValueError("autoscale control body must be a JSON "
                             "object")
        unknown = set(body) - {"freeze", "pin", "unpin"}
        if unknown:
            raise ValueError(f"unknown autoscale control keys: "
                             f"{sorted(unknown)} (know: freeze, pin, "
                             "unpin)")
        if "freeze" in body:
            if not isinstance(body["freeze"], bool):
                raise ValueError('"freeze" must be true or false')
            self.freeze(body["freeze"], by="manual")
        for key, op in (("pin", self._pinned.update),
                        ("unpin", self._pinned.difference_update)):
            if key in body:
                names = body[key]
                if not isinstance(names, list) \
                        or not all(isinstance(n, str) for n in names):
                    raise ValueError(f'"{key}" must be a list of '
                                     "replica names")
                op(names)
        self._export_gauges()
        return self.status()

    def status(self) -> dict:
        """The ``GET /autoscale`` body: live control-loop state plus
        the audit tail. Never raises; safe to scrape."""
        now = self._clock()
        drain = None
        if self._drain is not None:
            victim, deadline, add_role = self._drain
            drain = {"victim": victim,
                     "deadline_in_s": max(0.0, deadline - now),
                     "add_role_after": add_role or None}
        return {
            "enabled": True,
            "frozen": self._frozen,
            "frozen_by": self._frozen_by or None,
            "frozen_for_s": (now - self._frozen_since
                             if self._frozen_since is not None else None),
            "pinned": sorted(self._pinned),
            "evaluations": self.evals,
            "last_eval_t": self._last_eval,
            "streaks": dict(self._streak),
            "cooldown_remaining_s": {
                d: max(0.0, until - now)
                for d, until in self._cooldown_until.items()},
            "flap_budget": self.cfg.flap_budget,
            "flap_budget_remaining": self._flap_budget_remaining(now),
            "incident_latch_remaining_s":
                max(0.0, self._incident_until - now),
            "draining": drain,
            "decisions": self.audit_entries()[-32:],
            "config": dataclasses.asdict(self.cfg),
        }

    # -------------------------------------------------------------- loop
    def on_step(self) -> None:
        """One fleet iteration's control work: drain progress every
        step (one idle probe), a full evaluation at ``tick_s``
        cadence."""
        now = self._clock()
        if self._drain is not None:
            self._tick_drain(now)
        if self._last_eval is not None \
                and now - self._last_eval < self.cfg.tick_s:
            return
        self._last_eval = now
        self.evals += 1
        self.registry.counter("Fleet/autoscale_evals").inc()
        self._evaluate(now)
        self._export_gauges()

    # The decision order inside one evaluation is deliberate:
    # trust gate -> arm streaks -> load-reversal drain abort ->
    # freeze/fleet-drain holds -> add (safety first) -> incident latch
    # -> rebalance -> remove.
    def _evaluate(self, now: float) -> None:
        fleet = self.fleet
        report = fleet.scaling_report()
        # ---- score-trust gate: no report / unmeasured rho / saturated
        if report is None:
            self._streak = dict.fromkeys(self._streak, 0)
            self._record("signal_untrusted", "hold", ALARM,
                         reason="no scaling report (serving.loadscope "
                                "off, or no replica measured)",
                         inputs=_report_inputs(None), dedup=True)
            return
        what_ifs = {w.get("action"): w
                    for w in (report.get("what_ifs") or [])}
        fleet_agg = report.get("fleet") or {}
        if not what_ifs or fleet_agg.get("rho") is None:
            self._streak = dict.fromkeys(self._streak, 0)
            reasons = sorted({r for s in (report.get("replicas")
                                          or {}).values()
                              for r in (s.get("unmeasured") or [])})
            self._record("signal_untrusted", "hold", ALARM,
                         reason="utilization unmeasured: "
                                + ("; ".join(reasons) or "no what-ifs"),
                         inputs=_report_inputs(report), dedup=True)
            return
        add_wi = what_ifs.get("add_replica")
        rm_wi = what_ifs.get("remove_replica")
        rb_wi = what_ifs.get("rebalance_prefill_decode")
        if add_wi is not None and add_wi.get("saturated_now"):
            # past the knee the queue-wait forecast is null — the
            # estimator cannot price ANY move. Alarm, never actuate.
            self._streak = dict.fromkeys(self._streak, 0)
            self._record("signal_untrusted", "hold", ALARM,
                         reason=f"saturated (rho="
                                f"{fleet_agg.get('rho'):.3f}): forecast "
                                "is null past the knee — operator "
                                "attention required",
                         inputs=_report_inputs(report, add_wi),
                         dedup=True)
            return
        # ---- arm the per-direction streaks (hysteresis state)
        c = self.cfg
        armed_add = (add_wi is not None
                     and add_wi.get("score", 0.0) >= c.add_score_min)
        armed_rm = (rm_wi is not None
                    and rm_wi.get("score", 0.0) >= c.remove_score_min)
        armed_rb = (rb_wi is not None
                    and rb_wi.get("score", 0.0) >= c.rebalance_score_min)
        self._streak["add"] = self._streak["add"] + 1 if armed_add else 0
        self._streak["remove"] = (self._streak["remove"] + 1
                                  if armed_rm else 0)
        self._streak["rebalance"] = (self._streak["rebalance"] + 1
                                     if armed_rb else 0)
        # ---- load reversal beats everything: an armed scale-up signal
        # while a victim drains reopens it immediately (no hysteresis —
        # the drain itself was hysteresis-guarded; keeping capacity is
        # the safe direction)
        if self._drain is not None and armed_add:
            self._abort_drain(reason=f"load reversed mid-drain "
                                     f"(add score "
                                     f"{add_wi.get('score'):.0f} >= "
                                     f"{c.add_score_min:g})",
                              inputs=_report_inputs(report, add_wi))
            return
        if self._frozen:
            if armed_add or armed_rm or armed_rb:
                which = ("add_replica" if armed_add else
                         "remove_replica" if armed_rm else
                         "rebalance_prefill_decode")
                self._record("frozen", which, SUPPRESSED,
                             reason=f"frozen by {self._frozen_by}; "
                                    "signal held",
                             inputs=_report_inputs(
                                 report, what_ifs.get(which)),
                             dedup=True)
            return
        if fleet.draining:
            # a fleet-wide drain (shutdown in progress) outranks the
            # control loop entirely
            if armed_add or armed_rm or armed_rb:
                self._record("fleet_draining", "hold", SUPPRESSED,
                             reason="fleet-wide drain in progress",
                             inputs=_report_inputs(report), dedup=True)
            return
        if self._drain is not None:
            return      # a drain is in flight; one actuation at a time
        # ---- scale up (the safe direction: allowed during the
        # incident latch — failover just REDUCED capacity)
        if armed_add and self._streak["add"] >= c.up_ticks:
            self._try_add(now, report, add_wi)
            return
        # ---- the incident latch gates everything that removes
        # capacity from a role
        if (armed_rm or armed_rb) and now < self._incident_until:
            which = "remove_replica" if armed_rm \
                else "rebalance_prefill_decode"
            self._record("incident_latch", which, SUPPRESSED,
                         reason="scale-down latched after an incident "
                                f"({max(0.0, self._incident_until - now):.0f}s "
                                "remaining) — failover is not a lull",
                         inputs=_report_inputs(report,
                                               what_ifs.get(which)),
                         dedup=True)
            return
        if armed_rb and self._streak["rebalance"] >= c.down_ticks:
            self._try_rebalance(now, report, rb_wi)
            return
        if armed_rm and self._streak["remove"] >= c.down_ticks:
            self._try_remove(now, report, rm_wi)

    # --------------------------------------------------------- actuation
    def _guard_common(self, now: float, direction: str, action: str,
                      inputs: dict) -> bool:
        """Cooldown + flap-budget guards shared by every actuation;
        True = clear to actuate (and the flap, if this is a reversal,
        is booked)."""
        until = self._cooldown_until[direction]
        if now < until:
            self._record("cooldown", action, SUPPRESSED,
                         reason=f"{direction} cooldown "
                                f"({until - now:.0f}s remaining)",
                         inputs=inputs, dedup=True)
            return False
        reversal = (self._last_direction is not None
                    and self._last_direction != direction)
        if reversal:
            if self._flap_budget_remaining(now) <= 0:
                # budget exhausted: freeze the loop rather than keep
                # oscillating — unfreezing is a manual decision
                self._record("flap_budget", action, SUPPRESSED,
                             reason=f"flap budget ({self.cfg.flap_budget}"
                                    f" per {self.cfg.flap_window_s:g}s) "
                                    "exhausted — loop frozen; unfreeze "
                                    "via POST /autoscale",
                             inputs=inputs)
                self.freeze(True, by="flap_budget")
                return False
            self._flaps.append(now)
            self.registry.counter("Fleet/autoscale_flaps").inc()
        return True

    def _try_add(self, now: float, report: dict, wi: dict) -> None:
        fleet = self.fleet
        inputs = _report_inputs(report, wi)
        if len(fleet.replicas) >= self.cfg.max_replicas:
            self._record("max_replicas", "add_replica", SUPPRESSED,
                         reason=f"at max_replicas="
                                f"{self.cfg.max_replicas}; cannot add "
                                "— operator attention required",
                         inputs=inputs, dedup=True)
            return
        if not self._guard_common(now, "up", "add_replica", inputs):
            return
        role = None
        if fleet._disagg:
            # add to the hotter phase; decode when unknown (decode
            # replicas also absorb handoff backlog)
            rp = (report.get("fleet") or {}).get("rho_prefill")
            rd = (report.get("fleet") or {}).get("rho_decode")
            role = (self._roles[1]
                    if rp is not None and rd is not None and rp > rd
                    else self._roles[2])
        name = fleet.add_replica(role=role)
        self._after_actuation(now, "up")
        self.registry.counter("Fleet/autoscale_adds").inc()
        self._record("hysteresis_up", "add_replica", ACTUATED,
                     target=name,
                     reason=f"add score {wi.get('score'):.0f} armed "
                            f"{self._streak['add']} ticks (warm join "
                            "from the shared program cache)",
                     inputs=inputs)
        self._streak["add"] = 0

    def _pick_victim(self, role: "Optional[str]") -> Optional[str]:
        """Least-loaded legally-removable replica of ``role`` (or of
        the fleet when None), skipping pinned names. Ranked best-first
        by the router's own policy — removing the least-loaded victim
        strands the least work."""
        fleet = self.fleet
        killable = set(fleet._killable())
        names = [i["name"] for i in
                 (fleet._ranked(role, admission=False) if role is not None
                  else [j for r in set(fleet.roles.values())
                        for j in fleet._ranked(r, admission=False)])]
        for name in names:
            if name in killable and name not in self._pinned:
                return name
        return None

    def _try_remove(self, now: float, report: dict, wi: dict) -> None:
        fleet = self.fleet
        inputs = _report_inputs(report, wi)
        if len(fleet.replicas) <= self.cfg.min_replicas:
            self._record("min_replicas", "remove_replica", SUPPRESSED,
                         reason=f"at min_replicas="
                                f"{self.cfg.min_replicas}",
                         inputs=inputs, dedup=True)
            return
        if not self._guard_common(now, "down", "remove_replica", inputs):
            return
        role = None
        if fleet._disagg:
            # shed from the colder phase (the hotter one needs its
            # capacity); _killable keeps the last replica of each role
            rp = (report.get("fleet") or {}).get("rho_prefill")
            rd = (report.get("fleet") or {}).get("rho_decode")
            role = (self._roles[1]
                    if rp is not None and rd is not None and rp < rd
                    else self._roles[2])
        victim = self._pick_victim(role)
        if victim is None:
            self._record("no_victim", "remove_replica", SUPPRESSED,
                         reason="no removable un-pinned replica "
                                f"(pinned: {sorted(self._pinned)})",
                         inputs=inputs, dedup=True)
            return
        self._begin_drain(now, victim, add_role="", inputs=inputs,
                          rule="hysteresis_down",
                          reason=f"remove score {wi.get('score'):.0f} "
                                 f"armed {self._streak['remove']} ticks")
        self._streak["remove"] = 0

    def _try_rebalance(self, now: float, report: dict, wi: dict) -> None:
        fleet = self.fleet
        inputs = _report_inputs(report, wi)
        if not fleet._disagg:
            return
        if not self._guard_common(now, "down",
                                  "rebalance_prefill_decode", inputs):
            return
        direction = wi.get("direction") or ""
        donor_role, add_role = (
            (self._roles[2], self._roles[1])
            if direction == "decode_to_prefill"
            else (self._roles[1], self._roles[2]))
        victim = self._pick_victim(donor_role)
        if victim is None:
            self._record("no_victim", "rebalance_prefill_decode",
                         SUPPRESSED,
                         reason=f"no removable {donor_role} donor",
                         inputs=inputs, dedup=True)
            return
        self._begin_drain(now, victim, add_role=add_role, inputs=inputs,
                          rule="rebalance",
                          reason=f"{direction}: score "
                                 f"{wi.get('score'):.0f} armed "
                                 f"{self._streak['rebalance']} ticks")
        self._streak["rebalance"] = 0

    def _begin_drain(self, now: float, victim: str, add_role: str,
                     inputs: dict, rule: str, reason: str) -> None:
        """Drain-before-remove: close the victim's intake; removal
        happens in :meth:`_tick_drain` once idle or at the deadline."""
        fleet = self.fleet
        deadline = now + self.cfg.drain_deadline_s
        fleet.begin_drain_replica(victim)
        self._drain = (victim, deadline, add_role)
        self.registry.counter("Fleet/autoscale_drains").inc()
        self._record(rule,
                     "rebalance_prefill_decode" if add_role
                     else "remove_replica",
                     DRAIN_STARTED, target=victim,
                     reason=reason + f"; drain deadline "
                                     f"{self.cfg.drain_deadline_s:g}s",
                     inputs=inputs)

    def _abort_drain(self, reason: str, inputs: dict) -> None:
        victim, _deadline, add_role = self._drain
        self._drain = None
        if victim in self.fleet.replicas:
            self.fleet.end_drain_replica(victim)
        self.registry.counter("Fleet/autoscale_drain_aborts").inc()
        self._record("load_reversal",
                     "rebalance_prefill_decode" if add_role
                     else "remove_replica",
                     DRAIN_ABORTED, target=victim, reason=reason,
                     inputs=inputs)
        # the reversal consumed the down intent; restart its hysteresis
        self._streak["remove"] = self._streak["rebalance"] = 0
        self._export_gauges()

    def _tick_drain(self, now: float) -> None:
        victim, deadline, add_role = self._drain
        fleet = self.fleet
        eng = fleet.replicas.get(victim)
        if eng is None:
            # removed/killed underneath us (operator or chaos): the
            # on_incident path already recorded the kill case
            self._drain = None
            self._export_gauges()
            return
        idle = eng.sched.idle and eng._prefill is None
        if not idle and now < deadline:
            return
        requeued = fleet.remove_replica(victim)
        self._after_actuation(now, "down")
        self.registry.counter("Fleet/autoscale_removes").inc()
        outcome = REMOVED if idle else REMOVED_AT_DEADLINE
        reason = ("drained clean (nothing requeued)" if idle else
                  f"drain deadline hit; {len(requeued)} stragglers "
                  "requeued onto survivors")
        self._drain = None
        self._record("drain_complete",
                     "rebalance_prefill_decode" if add_role
                     else "remove_replica",
                     outcome, target=victim,
                     reason=reason,
                     inputs={"requeued_rids": list(requeued)})
        if add_role:
            name = fleet.add_replica(role=add_role)
            self.registry.counter("Fleet/autoscale_rebalances").inc()
            self._record("rebalance_join", "add_replica", ACTUATED,
                         target=name,
                         reason=f"rebalance: {victim} removed, {name} "
                                f"joined as {add_role} (warm join)",
                         inputs={})
        self._export_gauges()

    def _after_actuation(self, now: float, direction: str) -> None:
        cd = (self.cfg.cooldown_up_s if direction == "up"
              else self.cfg.cooldown_down_s)
        self._cooldown_until[direction] = now + cd
        self._last_direction = direction
        self._last_actuation_t = now
        self._last_quiet_key = None
