"""Slot-based decode state: one persistent KV cache, per-slot everything.

Reference analog: DeepSpeed-MII / FastGen's blocked-KV "ragged batching"
state. TPU-native translation: instead of a paged block table (dynamic
indirection is hostile to XLA's static shapes), the serving state is ONE
``(L, slots, KV, max_len, hd)`` cache — the same layout ``init_cache``
allocates, via the shared :func:`~..inference.decode.cache_layout` — plus
per-slot ``length`` / ``tok`` / ``rng`` / ``done`` vectors. A finished
slot is immediately reusable: insertion overwrites the slot's FULL cache
extent with the freshly prefilled request's cache (one donated
``dynamic_update_slice``), so stale KV from the previous occupant can
never leak into a successor's attention, and the decode step stays one
static-shape program no matter which requests come and go.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..inference.decode import GenCarry, KVCache, cache_layout

__all__ = ["init_slots", "insert_request"]


def init_slots(cfg, slots: int, max_len: int, dtype=None) -> GenCarry:
    """Empty slot state: all slots idle (``done``), length 0.

    The carry is a plain :class:`~..inference.decode.GenCarry` whose cache
    ``length`` is a (slots,) vector — the decode stack's per-slot paths key
    off that shape, so the same ``decode_step`` serves both worlds."""
    shape, dtype = cache_layout(cfg, slots, max_len, dtype)
    cache = KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                    length=jnp.zeros((slots,), jnp.int32))
    return GenCarry(tok=jnp.zeros((slots,), jnp.int32), cache=cache,
                    rng=jnp.zeros((slots, 2), jnp.uint32),
                    done=jnp.ones((slots,), bool))


def insert_request(state: GenCarry, slot, pf: GenCarry) -> GenCarry:
    """Write a freshly prefilled request (batch-1 carry, same ``max_len``)
    into slot ``slot``.

    ``slot`` is a traced i32 scalar, so ONE compiled program inserts into
    any slot. The caller jits this with the state donated: the slot
    cache updates in place — no second copy of the (L, slots, KV, max_len,
    hd) buffers ever exists. The update spans the slot's full ``max_len``
    extent (the prefill cache is allocated at the slot's capacity), which
    is what guarantees a retired request's stale KV is fully overwritten
    before the new occupant's first decode step."""
    kc = state.cache
    k = lax.dynamic_update_slice(kc.k, pf.cache.k.astype(kc.k.dtype),
                                 (0, slot, 0, 0, 0))
    v = lax.dynamic_update_slice(kc.v, pf.cache.v.astype(kc.v.dtype),
                                 (0, slot, 0, 0, 0))
    length = lax.dynamic_update_slice(
        kc.length, pf.cache.length.reshape(1).astype(jnp.int32), (slot,))
    tok = lax.dynamic_update_slice(state.tok, pf.tok.astype(jnp.int32),
                                   (slot,))
    rng = lax.dynamic_update_slice(state.rng, pf.rng, (slot, 0))
    done = lax.dynamic_update_slice(state.done, pf.done, (slot,))
    return GenCarry(tok=tok, cache=KVCache(k=k, v=v, length=length),
                    rng=rng, done=done)
