"""Multi-replica serving fleet: SLO-aware router over N ServingEngines.

Reference analog: the DeepSpeed-MII / FastGen serving deployment layer
(the survey's "from one engine to a service" step) — N replicas behind
one ``submit/step/drain/pop_result`` surface — with ZeRO-Infinity's
streaming discipline applied to KV handoff: finished prefill state moves
between roles as a page transfer instead of being recomputed.

:class:`FleetEngine` fronts N in-process
:class:`~.engine.ServingEngine` replicas built over ONE shared
:class:`~..inference.engine.InferenceEngine` (params and compiled
programs are shared; queues, slots, page pools, and metrics registries
are per-replica). What the fleet adds:

- **SLO-aware routing** — every admission consults each replica's live
  ``health()`` snapshot plus its ``Serve/slo_*_burn`` and
  ``Serve/goodput_frac`` gauges: least-loaded wins, and a draining,
  degraded, queue-full, or pool-pressured replica is never chosen while
  an alternative exists. All replicas draining → a typed
  :class:`~..resilience.guards.QueueFullError` shed, exactly like a
  single engine's drain.
- **Session affinity** — requests carrying a ``session_id`` stick to
  the replica whose radix tree already holds their prefix (that is
  where their prefill is nearly free). When the sticky replica is
  unhealthy the router falls back to policy and records the move in
  ``Fleet/affinity_misses``.
- **Replica loss/join** — ``remove_replica`` / a chaos kill requeues
  the victim's queued and in-flight requests onto survivors with a
  typed ``REQUEUED`` transition and a bumped ``Request.attempts`` (zero
  request loss — the ``bench_fleet.py --smoke`` oracle); per-request
  RNG folds from the seed, so a rerun's bits match a fresh submission.
  ``add_replica`` warms from the fleet's shared compiled-program cache:
  a joining replica serves traffic with ZERO new compiles.
- **Disaggregated prefill/decode** — ``prefill_replicas=k`` dedicates k
  replicas to chunked prefill; a finished prefill is exported from the
  source page pool (:func:`~.pages.export_slot` — gather the request's
  page-table row), moved host-side, and imported into a decode
  replica's pool (:func:`~.pages.import_slot` — scatter into a fresh
  allocation, shared-prefix entries redirected to scratch). The RNG
  chain travels with the payload, so disaggregated output is
  bit-identical to a single engine's (the parity oracle in tier-1).

``Fleet/*`` metrics land in the fleet's own
:class:`~..observability.metrics.MetricsRegistry` (same sinks as
everything else via :meth:`publish_metrics`); fleet goodput is the
PR-8 rollup math (:func:`~..observability.goodput.rollup_goodput`) over
per-replica ledgers. Everything is host-side — the fleet layer adds no
device programs beyond the export/import pair, no syncs, and no
threads.
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import OrderedDict
from typing import Optional

from ..inference.config import ServingConfig
from ..inference.engine import InferenceEngine
from ..observability.metrics import MetricsRegistry
from ..resilience.chaos import FleetChaosConfig, FleetChaosMonkey
from ..resilience.guards import QueueFullError, RequestStatus
from ..utils.logging import warning_once
from .engine import _MAX_RESULTS, ServingEngine
from .scheduler import Request

__all__ = ["FleetEngine"]

# Uniform fleets have one role; disaggregated fleets split it. Routing
# matches roles exactly: a prefill replica never takes decode residency
# and vice versa.
ROLE_SERVE = "serve"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


class FleetEngine:
    """N in-process serving replicas behind one engine-shaped surface.

    ``engine`` supplies params/mesh/model (shared by every replica);
    ``serving`` is the per-replica :class:`ServingConfig` (or dict) —
    replicas are homogeneous by construction. ``prefill_replicas > 0``
    switches to disaggregated roles (requires the paged KV cache — the
    handoff is a page transfer). ``chaos`` takes a
    :class:`~..resilience.chaos.FleetChaosConfig` for deterministic
    replica-kill tests; ``clock`` is injectable and shared with every
    replica, so fake-clock tests drive the whole fleet.
    """

    def __init__(self, engine: InferenceEngine,
                 serving: ServingConfig | dict | None = None,
                 replicas: int = 2, prefill_replicas: int = 0,
                 names: Optional[list] = None, chaos=None,
                 registry=None, clock=None, session_cap: int = 4096,
                 programs: Optional[OrderedDict] = None):
        if replicas < 1:
            raise ValueError(f"fleet needs >= 1 replica, got {replicas}")
        if prefill_replicas < 0 or (prefill_replicas
                                    and prefill_replicas >= replicas):
            raise ValueError(
                f"prefill_replicas={prefill_replicas} must be >= 0 and "
                f"leave at least one decode replica (replicas={replicas})")
        self.engine = engine
        if serving is None:
            # the replicas would fall back to engine.config.serving (the
            # ServingEngine default) — validate against THAT config, not
            # a default-constructed one
            serving = engine.config.serving
        self._spec = serving
        cfg0 = ServingConfig.from_any(
            dataclasses.replace(serving) if isinstance(serving,
                                                       ServingConfig)
            else serving)
        self._disagg = prefill_replicas > 0
        if self._disagg and cfg0.page_size == 0:
            raise ValueError(
                "disaggregated prefill/decode needs the paged KV cache "
                "(set serving.page_size) — the handoff is a page transfer")
        tcfg = cfg0.telemetry
        # checked BEFORE any replica binds (below) and again at every
        # later _build_replica, so add_replica() on a 1-replica fleet
        # cannot bind-crash on the same port either
        self._fixed_port_telemetry = bool(
            tcfg is not None and tcfg.enabled and tcfg.port)
        if replicas > 1 and self._fixed_port_telemetry:
            raise ValueError(
                "serving.telemetry with a fixed port cannot be shared by "
                f"{replicas} replicas — use port=0 (ephemeral) or start "
                "telemetry per replica via engine.serve_telemetry()")
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._clock = clock if clock is not None else time.perf_counter
        self._engine_clock = clock
        # fleet-shared seams: ONE compiled-program cache (a joining
        # replica warms from it) and ONE rid namespace (a rid names a
        # request fleet-wide; requeue keeps the id). ``programs`` lets a
        # caller seed the cache from another fleet over the SAME engine
        # and an IDENTICAL serving config (blue/green rollouts, test
        # suites) — programs bake in shapes AND the sampling policy, so
        # sharing across differing configs is a caller bug.
        self._programs: OrderedDict = \
            programs if programs is not None else OrderedDict()
        self._rid_next = [0]

        def _rid():
            rid = self._rid_next[0]
            self._rid_next[0] += 1
            return rid

        self._rid = _rid
        self.replicas: "OrderedDict[str, ServingEngine]" = OrderedDict()
        self.roles: dict = {}
        self._draining = False
        self._joined = 0              # monotonic: default-name uniqueness
        if names is not None and len(names) != replicas:
            raise ValueError(f"{len(names)} names for {replicas} replicas")
        try:
            for i in range(replicas):
                if self._disagg:
                    role = (ROLE_PREFILL if i < prefill_replicas
                            else ROLE_DECODE)
                    default = (f"p{i}" if i < prefill_replicas
                               else f"d{i - prefill_replicas}")
                else:
                    role, default = ROLE_SERVE, f"r{i}"
                self._build_replica(
                    names[i] if names is not None else default, role)
        except Exception:
            # a failed build (bad name, port bind, ...) must not leak
            # the replicas — and their telemetry listeners — already up
            for eng_built in self.replicas.values():
                eng_built.close()
            raise
        # router state: rid -> owning replica name; (role, session) ->
        # sticky replica, LRU-bounded so a million sessions can't leak
        self._owner: dict[int, str] = {}
        self._session: OrderedDict = OrderedDict()
        self._session_cap = int(session_cap)
        # finished requests awaiting pickup, bounded exactly like one
        # engine's store; evictions attribute to the OWNING replica
        self.results: "OrderedDict[int, Request]" = OrderedDict()
        self._max_results = _MAX_RESULTS
        # pending prefill→decode handoffs: (request, host payload)
        self._handoffs: list = []
        # requests the FLEET layer itself retired (handoff-deadline
        # timeouts, requeue sheds) — drained into the next step()'s
        # return so its "everything that retired" contract stays true
        self._retired_inline: list = []
        self.chaos: Optional[FleetChaosMonkey] = None
        cc = FleetChaosConfig.from_any(chaos)
        if cc is not None and cc.enabled:
            self.chaos = FleetChaosMonkey(cc)
        self._iterations = 0

    # ------------------------------------------------------------ replicas
    def _replica_cfg(self) -> ServingConfig | dict | None:
        """A FRESH config per replica (``reload_slo`` mutates in place —
        replicas must not share one instance)."""
        if isinstance(self._spec, ServingConfig):
            return dataclasses.replace(self._spec)
        return self._spec

    def _build_replica(self, name: str, role: str) -> ServingEngine:
        if name in self.replicas:
            raise ValueError(f"duplicate replica name {name!r}")
        if self.replicas and self._fixed_port_telemetry:
            raise ValueError(
                "serving.telemetry with a fixed port cannot be shared by "
                "multiple replicas — use port=0 (ephemeral) or start "
                "telemetry per replica via engine.serve_telemetry()")
        eng = ServingEngine(self.engine, self._replica_cfg(),
                            clock=self._engine_clock,
                            programs=self._programs, rid_source=self._rid,
                            name=name)
        if role == ROLE_PREFILL:
            eng.on_placed = (lambda req, slot, _n=name:
                             self._on_prefill_placed(_n, req, slot))
        if self._draining:
            eng.begin_drain()
        self.replicas[name] = eng
        self.roles[name] = role
        self._joined += 1
        return eng

    def add_replica(self, name: Optional[str] = None,
                    role: Optional[str] = None) -> str:
        """Elastic join: build one more replica over the SAME inference
        engine and the fleet's shared program cache — it serves traffic
        with zero new compiles (warm join; the tier-1 test pins
        ``compiles == 0`` on the joined replica). Returns its name."""
        if role is None:
            role = ROLE_DECODE if self._disagg else ROLE_SERVE
        valid = {ROLE_PREFILL, ROLE_DECODE} if self._disagg \
            else {ROLE_SERVE}
        if role not in valid:
            raise ValueError(f"role {role!r} not in {sorted(valid)} for "
                             "this fleet")
        if name is None:
            stem = {ROLE_SERVE: "r", ROLE_PREFILL: "p",
                    ROLE_DECODE: "d"}[role]
            name = f"{stem}{self._joined}"
            while name in self.replicas:
                self._joined += 1
                name = f"{stem}{self._joined}"
        self._build_replica(name, role)
        self.registry.counter("Fleet/replica_joins").inc()
        return name

    def remove_replica(self, name: str) -> list:
        """Planned scale-down: take ``name`` out of the fleet; its
        queued and in-flight requests requeue onto survivors (typed
        ``REQUEUED``, ``attempts`` bumped, original deadlines kept).
        Returns the requeued rids."""
        return self._remove(name)

    def kill_replica(self, name: str) -> list:
        """Abrupt replica loss (the chaos fault): mechanically identical
        to :meth:`remove_replica` — the router's knowledge of its
        outstanding requests IS the failover source — but counted as a
        kill so dashboards separate incidents from scale-downs. A
        REFUSED kill (unknown name, last replica of a role) raises
        without counting: dashboards never show a phantom incident."""
        out = self._remove(name)
        self.registry.counter("Fleet/replica_kills").inc()
        return out

    def _remove(self, name: str) -> list:
        if name not in self.replicas:
            raise KeyError(f"no replica named {name!r} "
                           f"(have {list(self.replicas)})")
        if len(self.replicas) == 1:
            raise RuntimeError("cannot remove the last replica")
        if self._disagg:
            role = self.roles[name]
            others = [n for n in self.replicas
                      if n != name and self.roles[n] == role]
            if not others:
                raise RuntimeError(
                    f"cannot remove the last {role} replica of a "
                    "disaggregated fleet")
        eng = self.replicas.pop(name)
        self.roles.pop(name)
        # results that retired before the loss are NOT lost: harvest
        for rid in list(eng.results):
            self._adopt_result(eng.pop_result(rid), name)
        # live requests: the prefill lane + every slot + the queue
        live = []
        if eng._prefill is not None:
            live.append(eng._prefill[0])
            eng._prefill = None
        live += eng.sched.take_live()
        requeued = []
        requeue_role = ROLE_PREFILL if self._disagg else ROLE_SERVE
        # ONE ranking pass for the whole failover burst (the pattern
        # _pump_handoffs uses): re-ranking per orphan would re-snapshot
        # every survivor's registry exactly when the fleet is absorbing
        # a spike. take_live is oldest-first; iterating it REVERSED
        # (newest-first) against Scheduler.requeue's push-to-head leaves
        # each survivor's queue head oldest-first — the deadline-closest
        # request admits first.
        ranked = [i["name"]
                  for i in self._ranked(requeue_role, admission=False)]
        for req in reversed(live):
            self._requeue(req, requeue_role, ranked)
            requeued.append(req.rid)
        requeued.reverse()
        eng.close()
        return requeued

    def _requeue(self, req: Request, role: str,
                 ranked: "Optional[list]" = None) -> None:
        """Move one orphaned request onto a survivor: affinity-aware
        (its session's prefix may live on another replica too), typed
        REQUEUED transition via the survivor's scheduler. Requeue
        bypasses ``max_queue`` — this is already-admitted work, not new
        intake. ``ranked`` lets :meth:`_remove` amortize one ranking
        pass over the whole failover burst."""
        if ranked is None:
            ranked = [i["name"]
                      for i in self._ranked(role, admission=False)]
        sticky = (self._session.get((role, req.session_id))
                  if req.session_id is not None else None)
        name = sticky if sticky in ranked else \
            (ranked[0] if ranked else None)
        if name is None:
            # no survivor of this role can ever host it: terminal shed
            req.status = RequestStatus.SHED
            req.error = "no surviving replica to requeue onto"
            req.finish_t = self._clock()
            self.registry.counter("Fleet/requeue_sheds").inc()
            self._adopt_result(req, "")
            self._retired_inline.append(req)
            return
        self.replicas[name].requeue(req)
        self._owner[req.rid] = name
        if req.session_id is not None:
            self._stick(role, req.session_id, name)
        self.registry.counter("Fleet/requeued").inc()

    # -------------------------------------------------------------- router
    def _replica_info(self, name: str) -> dict:
        """One replica's routing picture: direct host state (queue,
        slots, drain/degraded/pool flags — the same definitions
        ``health()`` reports, via the engine's shared properties) plus
        ONE registry snapshot for the SLO-burn and goodput gauges.
        Routing runs per admission, so it must not pay ``health()``'s
        full gauge-mirror pass on top."""
        eng = self.replicas[name]
        g = eng.stats.registry.snapshot()["gauges"]
        burn = 0.0
        for k, v in g.items():
            if k.startswith("Serve/slo_") and k.endswith("_burn") \
                    and isinstance(v, float) and not math.isnan(v):
                burn = max(burn, v)
        gp = g.get("Serve/goodput_frac")
        if not isinstance(gp, float) or math.isnan(gp):
            gp = 1.0
        queue_depth = eng.sched.queue_depth
        queue_full = bool(eng.cfg.max_queue
                          and queue_depth >= eng.cfg.max_queue)
        load = (queue_depth + eng.sched.occupancy
                + (1 if eng._prefill is not None else 0)) \
            / max(1, eng.cfg.slots)
        return {
            "name": name,
            "draining": eng.draining,
            # "would I route here if anyone else could take it": ready
            # (not draining / queue-full), no recent watchdog stall, no
            # page-pool pressure, no burning SLO
            "healthy": (not eng.draining and not queue_full
                        and not eng.degraded and not eng.pool_pressure
                        and burn <= 1.0),
            "load": load, "burn": burn, "goodput": gp,
        }

    def _ranked(self, role: str, exclude=(), admission: bool = True) \
            -> list:
        """Routing infos of ``role``'s replicas, best-first: healthy
        before unhealthy, then least-loaded, then lowest SLO burn, then
        highest goodput. ``admission=False`` keeps draining replicas in
        the pool (handoffs and requeues are backlog, which a drain must
        finish). Returns the info dicts so callers reuse ONE snapshot
        pass instead of re-reading registries per decision."""
        infos = [self._replica_info(n) for n in self.replicas
                 if self.roles[n] == role and n not in exclude]
        if admission:
            infos = [i for i in infos if not i["draining"]]
        infos.sort(key=lambda i: (0 if i["healthy"] else 1, i["load"],
                                  i["burn"], -i["goodput"], i["name"]))
        return infos

    def _stick(self, role: str, sid, name: str) -> None:
        key = (role, sid)
        self._session[key] = name
        self._session.move_to_end(key)
        if len(self._session) > self._session_cap:
            self._session.popitem(last=False)

    def _route(self, role: str, session_id=None, exclude=()) -> str:
        """Pick the admission target; raises a typed shed when no
        replica of ``role`` is accepting (all draining/removed)."""
        infos = self._ranked(role, exclude=exclude, admission=True)
        if not infos:
            self.registry.counter("Fleet/sheds").inc()
            raise QueueFullError(
                f"no {role} replica accepting admissions (all draining); "
                "request shed")
        by_name = {i["name"]: i for i in infos}
        choice = infos[0]["name"]
        if session_id is not None:
            sticky = self._session.get((role, session_id))
            if sticky is not None:
                # stick when the sticky replica is routable AND healthy;
                # otherwise fall back to policy and record the miss (the
                # prefix will be rebuilt at the new home)
                si = by_name.get(sticky)
                if si is not None and si["healthy"]:
                    choice = sticky
                if choice == sticky:
                    self.registry.counter("Fleet/affinity_hits").inc()
                else:
                    self.registry.counter("Fleet/affinity_misses").inc()
            self._stick(role, session_id, choice)
        return choice

    # -------------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               seed: int = 0, session_id=None,
               ttft_deadline_s: Optional[float] = None,
               total_deadline_s: Optional[float] = None) -> int:
        """Route one request into the fleet; returns its fleet-wide rid.
        Same contract as ``ServingEngine.submit`` plus ``session_id``
        (opaque, hashable): requests of one session prefer the replica
        holding their shared prefix. Raises the same typed
        :class:`QueueFullError` when every eligible replica sheds."""
        role = ROLE_PREFILL if self._disagg else ROLE_SERVE
        tried: set = set()
        last: Optional[QueueFullError] = None
        while True:
            try:
                name = self._route(role, session_id=session_id,
                                   exclude=tried)
            except QueueFullError:
                if last is not None:
                    raise last
                raise
            eng = self.replicas[name]
            try:
                rid = eng.submit(prompt, max_new_tokens, seed=seed,
                                 ttft_deadline_s=ttft_deadline_s,
                                 total_deadline_s=total_deadline_s)
                break
            except QueueFullError as e:
                # this replica flipped to full/draining between the
                # health read and the submit: try the next-best before
                # shedding fleet-wide
                last = e
                tried.add(name)
        req = eng.sched.queue[-1]
        req.session_id = session_id
        self._owner[rid] = name
        r = self.registry
        r.counter("Fleet/submitted").inc()
        r.counter(f"Fleet/routed_{name}").inc()
        return rid

    def cancel(self, rid: int) -> Optional[Request]:
        """Cancel wherever the request lives — its owning replica or the
        pending-handoff buffer."""
        for i, (req, _payload) in enumerate(self._handoffs):
            if req.rid == rid:
                del self._handoffs[i]
                self.registry.gauge("Fleet/handoff_pending").set(
                    len(self._handoffs))
                req.status = RequestStatus.CANCELLED
                req.error = "cancelled during prefill→decode handoff"
                req.finish_t = self._clock()
                self._adopt_result(req, self._owner.get(rid, ""))
                return req
        name = self._owner.get(rid)
        if name in self.replicas:
            req = self.replicas[name].cancel(rid)
            if req is not None:
                self.replicas[name].pop_result(rid)
                self._adopt_result(req, name)
            return req
        return None

    # ------------------------------------------------------------- serving
    def step(self) -> list:
        """One fleet iteration: chaos hook, pending handoffs, then one
        ``step()`` on every replica. Returns every request that retired
        anywhere in the fleet this iteration; results are also held in
        the fleet's own bounded store for :meth:`pop_result`."""
        out: list = []
        if self.chaos is not None:
            # only offer LEGALLY removable victims (never the last
            # replica, never the last of a disaggregated role) — a
            # chaos fault must inject failure, not crash the router
            victim = self.chaos.maybe_kill(self._killable())
            if victim is not None:
                self.kill_replica(victim)
        if self._handoffs:
            self._pump_handoffs()
        for name in list(self.replicas):
            eng = self.replicas[name]
            for req in eng.step():
                eng.pop_result(req.rid)
                self._adopt_result(req, name)
                out.append(req)
        if self._retired_inline:
            # retirements the fleet layer itself produced (handoff
            # timeouts, requeue sheds) ride the same return channel
            out.extend(self._retired_inline)
            self._retired_inline = []
        self._iterations += 1
        self.registry.counter("Fleet/iterations").inc()
        return out

    def _killable(self) -> list:
        """Replica names whose removal :meth:`_remove` would accept."""
        if len(self.replicas) <= 1:
            return []
        if not self._disagg:
            return list(self.replicas)
        counts: dict = {}
        for n in self.replicas:
            counts[self.roles[n]] = counts.get(self.roles[n], 0) + 1
        return [n for n in self.replicas if counts[self.roles[n]] > 1]

    def _on_prefill_placed(self, name: str, req: Request,
                           slot: int) -> None:
        """The disaggregation seam (``ServingEngine.on_placed``): a
        prefill replica just seated a finished prefill — export its
        pages to host, release the slot (the prompt's blocks stay in the
        source tree for future sharing), queue the handoff. The takeover
        happens via these side effects; the hook returns nothing."""
        eng = self.replicas[name]
        payload = eng.export_request(req)
        eng.release_request(req)
        self._handoffs.append((req, payload))
        self.registry.counter("Fleet/handoffs").inc()
        self.registry.gauge("Fleet/handoff_pending").set(
            len(self._handoffs))

    def _pump_handoffs(self) -> None:
        """Try to land every pending handoff on a decode replica:
        affinity-aware, best-ranked first, and a destination that cannot
        take it right now (no free slot / pool pressure) just leaves the
        payload host-held for the next iteration. Expired deadlines
        retire here — a handed-off request is in no scheduler's sweep.
        The ranking snapshot is taken ONCE per pump and refreshed only
        after a successful import changes a replica's load — not per
        pending request (handoffs pile up exactly when this loop runs
        hottest)."""
        remaining = []
        ranked = [i["name"]
                  for i in self._ranked(ROLE_DECODE, admission=False)]
        for req, payload in self._handoffs:
            now = self._clock()
            if req.deadline_total is not None and now >= req.deadline_total:
                req.status = RequestStatus.TIMEOUT
                req.error = "total deadline expired during handoff"
                req.finish_t = now
                self.registry.counter("Fleet/handoff_timeouts").inc()
                self._adopt_result(req, self._owner.get(req.rid, ""))
                self._retired_inline.append(req)
                continue
            order = list(ranked)
            sticky = (self._session.get((ROLE_DECODE, req.session_id))
                      if req.session_id is not None else None)
            if sticky in order:
                order.remove(sticky)
                order.insert(0, sticky)
            placed = False
            for name in order:
                if self.replicas[name].import_request(req, payload):
                    self._owner[req.rid] = name
                    if req.session_id is not None:
                        self._stick(ROLE_DECODE, req.session_id, name)
                    self.registry.counter("Fleet/handoff_imports").inc()
                    placed = True
                    ranked = [i["name"] for i in
                              self._ranked(ROLE_DECODE, admission=False)]
                    break
            if not placed:
                remaining.append((req, payload))
        self._handoffs = remaining
        self.registry.gauge("Fleet/handoff_pending").set(
            len(self._handoffs))

    def _adopt_result(self, req: Request, name: str) -> None:
        self.results[req.rid] = req
        if name:
            self._owner[req.rid] = name
        if len(self.results) > self._max_results:
            old_rid, _old = self.results.popitem(last=False)
            owner = self._owner.pop(old_rid, None)
            rep = self.replicas.get(owner)
            if rep is not None:
                # the eviction is attributed to the replica that served
                # the request — its Serve/results_evicted counter is the
                # one dashboards already watch
                rep.stats.on_results_evicted()
            self.registry.counter("Fleet/results_evicted").inc()
            warning_once(
                f"fleet results store hit its cap ({self._max_results}); "
                "evicting oldest finished requests — collect results via "
                "step()'s return value or pop_result()")

    def pop_result(self, rid: int) -> Optional[Request]:
        """Collect (and release) a finished request by rid, regardless
        of which replica retired it — routed by rid through the owner
        map, never a scan."""
        req = self.results.pop(rid, None)
        if req is None:
            name = self._owner.get(rid)
            if name in self.replicas:
                req = self.replicas[name].pop_result(rid)
        if req is not None:
            self._owner.pop(rid, None)
        return req

    # ---------------------------------------------------------- lifecycle
    def begin_drain(self) -> None:
        """Fleet-wide drain: every replica stops admitting (new submits
        shed typed); queued, running, and handed-off requests finish."""
        self._draining = True
        for eng in self.replicas.values():
            eng.begin_drain()

    def end_drain(self) -> None:
        self._draining = False
        for eng in self.replicas.values():
            eng.end_drain()

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def idle(self) -> bool:
        return (not self._handoffs
                and all(e.sched.idle and e._prefill is None
                        for e in self.replicas.values()))

    def drain(self, max_iterations: int = 1_000_000) -> dict:
        """Graceful fleet shutdown: drain mode, run until every replica
        is idle and no handoff is pending, return the fleet results."""
        self.begin_drain()
        it = 0
        while not self.idle:
            self.step()
            it += 1
            if it > max_iterations:
                raise RuntimeError(
                    f"fleet failed to drain in {max_iterations} "
                    "iterations — scheduler wedged?")
        return self.results

    def serve_batch(self, prompts, max_new_tokens=None, seeds=None,
                    session_ids=None) -> list:
        """Convenience mirror of ``ServingEngine.serve_batch`` across the
        fleet: submit, drive, return each request's tokens in submission
        order (results popped)."""
        import numpy as np

        from .engine import expand_per_request

        n = len(prompts)
        mn = expand_per_request(max_new_tokens, n, None, int)
        sd = expand_per_request(seeds, n, 0, int)
        sid = expand_per_request(session_ids, n, None)
        rids = [self.submit(p, mn[i], seed=sd[i], session_id=sid[i])
                for i, p in enumerate(prompts)]
        want = set(rids)
        got: dict = {}
        it = 0
        while len(got) < n:
            for req in self.step():
                if req.rid in want:
                    got[req.rid] = req
                    self.results.pop(req.rid, None)
                    self._owner.pop(req.rid, None)
            it += 1
            if it > 1_000_000:
                raise RuntimeError("fleet serve_batch failed to finish — "
                                   "scheduler wedged?")
        return [np.asarray(got[r].tokens, np.int32) for r in rids]

    # ------------------------------------------------------------- readout
    def health(self) -> dict:
        """Fleet liveness/readiness rollup + per-replica snapshots,
        mirrored to ``Fleet/*`` gauges (replicas/ready/queue/occupancy/
        handoffs) so the scrape surface carries the router's picture."""
        per = {name: eng.health() for name, eng in self.replicas.items()}
        ready = sum(1 for h in per.values() if h["ready"])
        out = {
            "replicas": len(per),
            "ready_replicas": ready,
            "ready": ready > 0 and not self._draining,
            "state": "draining" if self._draining else "serving",
            "queue_depth": sum(h["queue_depth"] for h in per.values()),
            "occupancy": sum(h["occupancy"] for h in per.values()),
            "handoff_pending": len(self._handoffs),
            "iterations": self._iterations,
            "roles": dict(self.roles),
            "per_replica": per,
        }
        self.registry.set_gauges({
            "Fleet/replicas": float(out["replicas"]),
            "Fleet/replicas_ready": float(ready),
            "Fleet/ready": float(out["ready"]),
            "Fleet/queue_depth": float(out["queue_depth"]),
            "Fleet/occupancy": float(out["occupancy"]),
            "Fleet/handoff_pending": float(len(self._handoffs)),
        })
        return out

    def fleet_goodput(self) -> Optional[dict]:
        """The PR-8 rollup math over per-replica goodput ledgers
        (wall-weighted fraction, summed buckets), exported as
        ``Fleet/goodput_*`` gauges. None when no replica has a ledger
        (``serving.goodput`` off)."""
        from ..observability.goodput import rollup_goodput

        snaps = [eng.goodput.snapshot() for eng in self.replicas.values()
                 if eng.goodput is not None]
        if not snaps:
            return None
        roll = rollup_goodput(snaps)
        gauges = {"Fleet/goodput_wall_s": roll["wall_s"],
                  "Fleet/goodput_productive_s": roll["productive_s"],
                  "Fleet/goodput_badput_total_s": roll["badput_total_s"]}
        if roll["goodput_frac"] is not None:
            gauges["Fleet/goodput_frac"] = roll["goodput_frac"]
        self.registry.set_gauges(gauges)
        return roll

    def metrics_snapshot(self) -> dict:
        # refresh the derived gauges FIRST (publish_metrics order) so
        # the "fleet" section carries current health/goodput, not the
        # previous call's
        self.health()
        gp = self.fleet_goodput()
        snap = self.registry.snapshot()
        out = {
            "iterations": self._iterations,
            "fleet": {**snap["counters"], **snap["gauges"]},
            "replicas": {name: {"role": self.roles[name],
                                "compiles": eng.compiles,
                                **eng.stats.snapshot()}
                         for name, eng in self.replicas.items()},
        }
        if gp is not None:
            out["goodput"] = gp
        return out

    def requests_table(self) -> list:
        """Fleet-wide in-flight table: every replica's rows plus the
        pending-handoff residents, each labeled with its replica."""
        rows = []
        for name, eng in self.replicas.items():
            for row in eng.requests_table():
                row["replica"] = name
                rows.append(row)
        for req, _payload in self._handoffs:
            rows.append({"rid": req.rid, "state": "handoff", "slot": None,
                         "prompt_len": req.prompt_len,
                         "max_new": req.max_new,
                         "tokens": len(req.tokens),
                         "submit_t": req.submit_t, "admit_t": req.admit_t,
                         "deadline_ttft": req.deadline_ttft,
                         "deadline_total": req.deadline_total,
                         "status": req.status.value,
                         "attempts": req.attempts,
                         # the SOURCE replica that produced the payload:
                         # a stuck handoff must be attributable
                         "replica": self._owner.get(req.rid)})
        return rows

    def publish_metrics(self, monitor, step: Optional[int] = None) -> int:
        """Push ``Fleet/*`` (health rollup + goodput refreshed first)
        through a monitor fan-out, same contract as the engines'."""
        from ..observability.metrics import publish_registry

        self.health()
        self.fleet_goodput()
        return publish_registry(self.registry, monitor, step,
                                default_step_counter="Fleet/iterations")

    def close(self) -> None:
        """Teardown every replica (telemetry listeners etc.); the fleet
        object is not reusable afterwards."""
        for eng in self.replicas.values():
            eng.close()
