"""Multi-replica serving fleet: SLO-aware router over N ServingEngines.

Reference analog: the DeepSpeed-MII / FastGen serving deployment layer
(the survey's "from one engine to a service" step) — N replicas behind
one ``submit/step/drain/pop_result`` surface — with ZeRO-Infinity's
streaming discipline applied to KV handoff: finished prefill state moves
between roles as a page transfer instead of being recomputed.

:class:`FleetEngine` fronts N in-process
:class:`~.engine.ServingEngine` replicas built over ONE shared
:class:`~..inference.engine.InferenceEngine` (params and compiled
programs are shared; queues, slots, page pools, and metrics registries
are per-replica). What the fleet adds:

- **SLO-aware routing** — every admission consults each replica's live
  ``health()`` snapshot plus its ``Serve/slo_*_burn`` and
  ``Serve/goodput_frac`` gauges: least-loaded wins, and a draining,
  degraded, queue-full, or pool-pressured replica is never chosen while
  an alternative exists. All replicas draining → a typed
  :class:`~..resilience.guards.QueueFullError` shed, exactly like a
  single engine's drain.
- **Session affinity** — requests carrying a ``session_id`` stick to
  the replica whose radix tree already holds their prefix (that is
  where their prefill is nearly free). Routing is residency-ranked:
  tree hit > host-tier hit (the prefix was evicted but demoted to the
  replica's pinned-host store, ``serving/hostkv.py``) > cold miss, so
  a session falls back to the replica that can restore at copy
  bandwidth before one that must recompute. When the sticky replica is
  unhealthy the router falls back to policy and records the move in
  ``Fleet/affinity_misses``; a resume the sticky replica restores from
  its host tier books NO ``Fleet/affinity_regret`` (it paid copy
  bytes, not prefill — that is the host tier doing its job).
- **Replica loss/join** — ``remove_replica`` / a chaos kill requeues
  the victim's queued and in-flight requests onto survivors with a
  typed ``REQUEUED`` transition and a bumped ``Request.attempts`` (zero
  request loss — the ``bench_fleet.py --smoke`` oracle); per-request
  RNG folds from the seed, so a rerun's bits match a fresh submission.
  ``add_replica`` warms from the fleet's shared compiled-program cache:
  a joining replica serves traffic with ZERO new compiles.
- **Disaggregated prefill/decode** — ``prefill_replicas=k`` dedicates k
  replicas to chunked prefill; a finished prefill is exported from the
  source page pool (:func:`~.pages.export_slot` — gather the request's
  page-table row), moved host-side, and imported into a decode
  replica's pool (:func:`~.pages.import_slot` — scatter into a fresh
  allocation, shared-prefix entries redirected to scratch). The RNG
  chain travels with the payload, so disaggregated output is
  bit-identical to a single engine's (the parity oracle in tier-1).

- **Distributed request tracing** — with ``serving.spans`` on, the
  fleet keeps its OWN span ring (router decisions, requeues, handoff
  export/pending/import hops) next to each replica's lifecycle ring, a
  bounded **route-audit ring** (every route / shed / affinity-fallback
  / requeue with the ranked candidates and per-replica exclusion
  reasons — :meth:`FleetEngine.route_audit`), a per-request
  **hop-latency decomposition** whose non-null hops tile the request's
  e2e wall (:meth:`FleetEngine.request_trace`, ``Fleet/hop_*``
  histograms), and :meth:`FleetEngine.merge_trace` — ONE
  Chrome/Perfetto trace with every replica as a named pid and each
  cross-replica request stitched into a flow. Disabled (the default),
  none of it exists.
- **Correlated incident capture** — with ``serving.flight_dir`` set,
  ANY replica's flight-recorder trigger (watchdog stall, nonfinite
  halt, SIGTERM, manual) redirects into one shared
  ``incident_<stamp>_<reason>/`` directory and fans out: every sibling
  replica dumps too, and the fleet adds ``incident.json``, its ring,
  the route audit, and the merged cross-replica trace. The doctor's
  incident section reconstructs the timeline and gates on an
  unreconciled capture.

``Fleet/*`` metrics land in the fleet's own
:class:`~..observability.metrics.MetricsRegistry` (same sinks as
everything else via :meth:`publish_metrics`); fleet goodput is the
PR-8 rollup math (:func:`~..observability.goodput.rollup_goodput`) over
per-replica ledgers. Everything is host-side — the fleet layer adds no
device programs beyond the export/import pair, no syncs, and no
threads.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
from collections import OrderedDict, deque
from pathlib import Path
from typing import Optional

from ..inference.config import ServingConfig
from ..inference.engine import InferenceEngine
from ..observability import spans as _spans
from ..observability.export import HOP_NAMES, hop_trace
from ..observability.metrics import MetricsRegistry
from ..resilience.chaos import FleetChaosConfig, FleetChaosMonkey
from ..resilience.guards import QueueFullError, RequestStatus
from ..utils.logging import log_dist, warning_once
from .engine import _MAX_RESULTS, ServingEngine
from .scheduler import Request

__all__ = ["FleetEngine"]

# Uniform fleets have one role; disaggregated fleets split it. Routing
# matches roles exactly: a prefill replica never takes decode residency
# and vice versa.
ROLE_SERVE = "serve"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"

# Router decision audit ring capacity (host dicts; ~minutes of context
# around an incident — the flight/incident dump carries it to disk).
_AUDIT_RING = 1024


class FleetEngine:
    """N in-process serving replicas behind one engine-shaped surface.

    ``engine`` supplies params/mesh/model (shared by every replica);
    ``serving`` is the per-replica :class:`ServingConfig` (or dict) —
    replicas are homogeneous by construction. ``prefill_replicas > 0``
    switches to disaggregated roles (requires the paged KV cache — the
    handoff is a page transfer). ``chaos`` takes a
    :class:`~..resilience.chaos.FleetChaosConfig` for deterministic
    replica-kill tests; ``clock`` is injectable and shared with every
    replica, so fake-clock tests drive the whole fleet.
    """

    def __init__(self, engine: InferenceEngine,
                 serving: ServingConfig | dict | None = None,
                 replicas: int = 2, prefill_replicas: int = 0,
                 names: Optional[list] = None, chaos=None,
                 registry=None, clock=None, session_cap: int = 4096,
                 programs: Optional[OrderedDict] = None,
                 tracing: Optional[bool] = None):
        if replicas < 1:
            raise ValueError(f"fleet needs >= 1 replica, got {replicas}")
        if prefill_replicas < 0 or (prefill_replicas
                                    and prefill_replicas >= replicas):
            raise ValueError(
                f"prefill_replicas={prefill_replicas} must be >= 0 and "
                f"leave at least one decode replica (replicas={replicas})")
        self.engine = engine
        if serving is None:
            # the replicas would fall back to engine.config.serving (the
            # ServingEngine default) — validate against THAT config, not
            # a default-constructed one
            serving = engine.config.serving
        self._spec = serving
        cfg0 = ServingConfig.from_any(
            dataclasses.replace(serving) if isinstance(serving,
                                                       ServingConfig)
            else serving)
        self._disagg = prefill_replicas > 0
        if self._disagg and cfg0.page_size == 0:
            raise ValueError(
                "disaggregated prefill/decode needs the paged KV cache "
                "(set serving.page_size) — the handoff is a page transfer")
        tcfg = cfg0.telemetry
        # checked BEFORE any replica binds (below) and again at every
        # later _build_replica, so add_replica() on a 1-replica fleet
        # cannot bind-crash on the same port either
        self._fixed_port_telemetry = bool(
            tcfg is not None and tcfg.enabled and tcfg.port)
        if replicas > 1 and self._fixed_port_telemetry:
            raise ValueError(
                "serving.telemetry with a fixed port cannot be shared by "
                f"{replicas} replicas — use port=0 (ephemeral) or start "
                "telemetry per replica via engine.serve_telemetry()")
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._clock = clock if clock is not None else time.perf_counter
        self._engine_clock = clock
        # fleet-shared seams: ONE compiled-program cache (a joining
        # replica warms from it) and ONE rid namespace (a rid names a
        # request fleet-wide; requeue keeps the id). ``programs`` lets a
        # caller seed the cache from another fleet over the SAME engine
        # and an IDENTICAL serving config (blue/green rollouts, test
        # suites) — programs bake in shapes AND the sampling policy, so
        # sharing across differing configs is a caller bug.
        self._programs: OrderedDict = \
            programs if programs is not None else OrderedDict()
        self._rid_next = [0]

        def _rid():
            rid = self._rid_next[0]
            self._rid_next[0] += 1
            return rid

        self._rid = _rid
        # ---- distributed tracing (docs/OBSERVABILITY.md fleet tracing).
        # Follows the replicas' span knob by default: serving.spans=True
        # gives every replica its ring AND the fleet this router/handoff
        # ring + the route-audit ring. Disabled (the default) builds
        # NEITHER — the fleet layer pays `is not None` checks only, zero
        # new programs (the bench_fleet --smoke compile freeze stays the
        # oracle).
        self._tracing = bool(cfg0.spans) if tracing is None \
            else bool(tracing)
        self.spans: "Optional[_spans.SpanRecorder]" = None
        self._audit: "Optional[deque]" = None
        self._audit_seq = 0
        if self._tracing:
            self.spans = _spans.SpanRecorder(cfg0.spans_ring,
                                             clock=self._clock)
            self._audit = deque(maxlen=_AUDIT_RING)
        # ---- traffic capture (observability/replay.py): the FLEET owns
        # the trace — one stream recording routed submits (with session
        # ids), terminal results, and chaos events (kills/joins/drains),
        # replayable against any topology. Replicas are built with
        # capture stripped (_replica_cfg) so nothing double-records.
        # Off (default) builds nothing.
        self.capture = None
        if cfg0.capture:
            from ..observability.replay import TrafficCapture, capture_meta

            self.capture = TrafficCapture(
                clock=self._clock, ring=cfg0.capture_ring,
                meta=capture_meta(cfg0, engine="fleet",
                                  replicas=replicas,
                                  prefill_replicas=prefill_replicas))
        # ---- correlated incident capture: when the replicas carry
        # flight recorders (serving.flight_dir), any one replica's dump
        # trigger (watchdog stall, nonfinite halt, SIGTERM, manual) is
        # redirected into ONE shared incident dir and fanned out to
        # every other replica + the fleet's own artifacts + a merged
        # trace. No flight_dir = no machinery.
        self._incident_base: Optional[Path] = \
            Path(cfg0.flight_dir) if cfg0.flight_dir is not None else None
        self._incident_open: Optional[Path] = None
        # (dir, fleet iteration) of the newest capture: a second
        # TRIGGER in the same iteration joins it instead of opening a
        # duplicate (two replicas tripping on one event, or a manual
        # /flight/dump racing the serving thread's watchdog)
        self._incident_last: "Optional[tuple[Path, int]]" = None
        self._incident_lock = threading.RLock()
        self._incidents = 0
        self.replicas: "OrderedDict[str, ServingEngine]" = OrderedDict()
        self.roles: dict = {}
        self._draining = False
        self._joined = 0              # monotonic: default-name uniqueness
        if names is not None and len(names) != replicas:
            raise ValueError(f"{len(names)} names for {replicas} replicas")
        try:
            for i in range(replicas):
                if self._disagg:
                    role = (ROLE_PREFILL if i < prefill_replicas
                            else ROLE_DECODE)
                    default = (f"p{i}" if i < prefill_replicas
                               else f"d{i - prefill_replicas}")
                else:
                    role, default = ROLE_SERVE, f"r{i}"
                self._build_replica(
                    names[i] if names is not None else default, role)
        except Exception:
            # a failed build (bad name, port bind, ...) must not leak
            # the replicas — and their telemetry listeners — already up
            for eng_built in self.replicas.values():
                eng_built.close()
            raise
        # router state: rid -> owning replica name; (role, session) ->
        # sticky replica, LRU-bounded so a million sessions can't leak
        self._owner: dict[int, str] = {}
        self._session: OrderedDict = OrderedDict()
        self._session_cap = int(session_cap)
        # finished requests awaiting pickup, bounded exactly like one
        # engine's store; evictions attribute to the OWNING replica
        self.results: "OrderedDict[int, Request]" = OrderedDict()
        self._max_results = _MAX_RESULTS
        # pending prefill→decode handoffs: (request, host payload)
        self._handoffs: list = []
        # requests the FLEET layer itself retired (handoff-deadline
        # timeouts, requeue sheds) — drained into the next step()'s
        # return so its "everything that retired" contract stays true
        self._retired_inline: list = []
        self.chaos: Optional[FleetChaosMonkey] = None
        cc = FleetChaosConfig.from_any(chaos)
        if cc is not None and cc.enabled:
            self.chaos = FleetChaosMonkey(cc)
        # ---- elastic autoscaler (serving/autoscaler.py): the actuation
        # loop over scaling_report(). Off (the default) builds nothing —
        # step() pays one `is not None`, zero threads/programs/syncs
        # (the bench_autoscale --smoke compile freeze is the oracle).
        self.autoscaler = None
        acfg = cfg0.autoscale
        if acfg is not None and getattr(acfg, "enabled", True):
            from .autoscaler import Autoscaler

            self.autoscaler = Autoscaler(self, acfg)
        self._iterations = 0

    # ------------------------------------------------------------ replicas
    def _replica_cfg(self) -> ServingConfig | dict | None:
        """A FRESH config per replica (``reload_slo`` mutates in place —
        replicas must not share one instance). Traffic capture is
        STRIPPED: the fleet records the trace at its own surface (one
        stream, session ids, chaos events); a per-replica capture would
        double-record every request."""
        if isinstance(self._spec, ServingConfig):
            cfg = dataclasses.replace(self._spec)
            cfg.capture = False
            return cfg
        if isinstance(self._spec, dict) and self._spec.get("capture"):
            return {**self._spec, "capture": False}
        return self._spec

    def _build_replica(self, name: str, role: str) -> ServingEngine:
        if name in self.replicas:
            raise ValueError(f"duplicate replica name {name!r}")
        if self.replicas and self._fixed_port_telemetry:
            raise ValueError(
                "serving.telemetry with a fixed port cannot be shared by "
                "multiple replicas — use port=0 (ephemeral) or start "
                "telemetry per replica via engine.serve_telemetry()")
        eng = ServingEngine(self.engine, self._replica_cfg(),
                            clock=self._engine_clock,
                            programs=self._programs, rid_source=self._rid,
                            name=name)
        if role == ROLE_PREFILL:
            eng.on_placed = (lambda req, slot, _n=name:
                             self._on_prefill_placed(_n, req, slot))
        if eng.flight is not None:
            # correlated incident capture: this replica's dump triggers
            # (watchdog stall, nonfinite halt, SIGTERM, manual) redirect
            # into a shared fleet incident dir and fan out to siblings
            eng.flight.redirect = (lambda reason, _n=name:
                                   self._incident_redirect(_n, reason))
        if eng.kvscope is not None:
            # affinity-aware regret (observability/kvscope.py): a resume
            # that re-pays ghost-covered prefill ON THE REPLICA the
            # session was sticky to means affinity routed the session
            # home only for home to have evicted its prefix
            eng.kvscope.on_regret_resume = (
                lambda sid, toks, _n=name:
                self._on_regret_resume(_n, sid, toks))
        if self._draining:
            eng.begin_drain()
        self.replicas[name] = eng
        self.roles[name] = role
        self._joined += 1
        return eng

    def add_replica(self, name: Optional[str] = None,
                    role: Optional[str] = None) -> str:
        """Elastic join: build one more replica over the SAME inference
        engine and the fleet's shared program cache — it serves traffic
        with zero new compiles (warm join; the tier-1 test pins
        ``compiles == 0`` on the joined replica). Returns its name."""
        if role is None:
            role = ROLE_DECODE if self._disagg else ROLE_SERVE
        valid = {ROLE_PREFILL, ROLE_DECODE} if self._disagg \
            else {ROLE_SERVE}
        if role not in valid:
            raise ValueError(f"role {role!r} not in {sorted(valid)} for "
                             "this fleet")
        if name is None:
            stem = {ROLE_SERVE: "r", ROLE_PREFILL: "p",
                    ROLE_DECODE: "d"}[role]
            name = f"{stem}{self._joined}"
            while name in self.replicas:
                self._joined += 1
                name = f"{stem}{self._joined}"
        self._build_replica(name, role)
        self.registry.counter("Fleet/replica_joins").inc()
        if self.capture is not None:
            # role recorded so a disaggregated autoscaled run replays
            # its joins into the right phase
            self.capture.on_chaos("add_replica", name, role=role)
        return name

    def remove_replica(self, name: str) -> list:
        """Planned scale-down: take ``name`` out of the fleet; its
        queued and in-flight requests requeue onto survivors (typed
        ``REQUEUED``, ``attempts`` bumped, original deadlines kept).
        Returns the requeued rids."""
        out = self._remove(name)
        if self.capture is not None:
            self.capture.on_chaos("remove_replica", name)
        return out

    def kill_replica(self, name: str) -> list:
        """Abrupt replica loss (the chaos fault): mechanically identical
        to :meth:`remove_replica` — the router's knowledge of its
        outstanding requests IS the failover source — but counted as a
        kill so dashboards separate incidents from scale-downs. A
        REFUSED kill (unknown name, last replica of a role) raises
        without counting: dashboards never show a phantom incident."""
        out = self._remove(name)
        self.registry.counter("Fleet/replica_kills").inc()
        if self.autoscaler is not None:
            # latch scale-down: the failover's requeue burst and arrival
            # dip must never be read as a remove signal
            self.autoscaler.on_incident("kill_replica", name)
        if self.capture is not None:
            # the chaos script half of the trace: replay re-kills this
            # replica at the same position in the stream
            self.capture.on_chaos("kill_replica", name)
        return out

    def _remove(self, name: str) -> list:
        if name not in self.replicas:
            raise KeyError(f"no replica named {name!r} "
                           f"(have {list(self.replicas)})")
        if len(self.replicas) == 1:
            raise RuntimeError("cannot remove the last replica")
        if self._disagg:
            role = self.roles[name]
            others = [n for n in self.replicas
                      if n != name and self.roles[n] == role]
            if not others:
                raise RuntimeError(
                    f"cannot remove the last {role} replica of a "
                    "disaggregated fleet")
        eng = self.replicas.pop(name)
        self.roles.pop(name)
        # results that retired before the loss are NOT lost: harvest
        for rid in list(eng.results):
            self._adopt_result(eng.pop_result(rid), name)
        # live requests: the prefill lane + every slot + the queue
        live = []
        if eng._prefill is not None:
            live.append(eng._prefill[0])
            eng._prefill = None
        live += eng.sched.take_live()
        requeued = []
        requeue_role = ROLE_PREFILL if self._disagg else ROLE_SERVE
        # ONE ranking pass for the whole failover burst (the pattern
        # _pump_handoffs uses): re-ranking per orphan would re-snapshot
        # every survivor's registry exactly when the fleet is absorbing
        # a spike. take_live is oldest-first; iterating it REVERSED
        # (newest-first) against Scheduler.requeue's push-to-head leaves
        # each survivor's queue head oldest-first — the deadline-closest
        # request admits first.
        ranked_infos = self._ranked(requeue_role, admission=False)
        for req in reversed(live):
            self._requeue(req, requeue_role, ranked_infos,
                          lost_replica=name)
            requeued.append(req.rid)
        requeued.reverse()
        # pending handoffs the victim EXPORTED are host-held payloads —
        # they survive its removal — but their owner map still points at
        # it. Clear the ghost entries and re-pump NOW, before the
        # scheduler is gone, so they land on survivors this call instead
        # of waiting (possibly forever, if the fleet idles) for the next
        # step's pump.
        if self._handoffs:
            for req, _payload in self._handoffs:
                if self._owner.get(req.rid) == name:
                    self._owner.pop(req.rid, None)
            self._pump_handoffs()
        eng.close()
        return requeued

    def _requeue(self, req: Request, role: str,
                 ranked: "Optional[list]" = None,
                 lost_replica: str = "") -> None:
        """Move one orphaned request onto a survivor: affinity-aware
        (its session's prefix may live on another replica too), typed
        REQUEUED transition via the survivor's scheduler. Requeue
        bypasses ``max_queue`` — this is already-admitted work, not new
        intake. ``ranked`` (routing-info dicts) lets :meth:`_remove`
        amortize one ranking pass over the whole failover burst."""
        if ranked is None:
            ranked = self._ranked(role, admission=False)
        names = [i["name"] for i in ranked]
        sticky = (self._session.get((role, req.session_id))
                  if req.session_id is not None else None)
        name = sticky if sticky in names else \
            (names[0] if names else None)
        if name is None:
            # no survivor of this role can ever host it: terminal shed
            req.status = RequestStatus.SHED
            req.error = "no surviving replica to requeue onto"
            req.finish_t = self._clock()
            self.registry.counter("Fleet/requeue_sheds").inc()
            self._audit_record("requeue_shed", rid=req.rid, role=role,
                               session_id=req.session_id,
                               tenant_id=req.tenant_id,
                               candidates=ranked,
                               lost_replica=lost_replica)
            self._adopt_result(req, "")
            self._retired_inline.append(req)
            return
        self.replicas[name].requeue(req)
        self._owner[req.rid] = name
        if req.session_id is not None:
            self._stick(role, req.session_id, name)
        self.registry.counter("Fleet/requeued").inc()
        self._audit_record("requeue", rid=req.rid, role=role,
                           session_id=req.session_id, chosen=name,
                           tenant_id=req.tenant_id,
                           sticky=sticky, candidates=ranked,
                           lost_replica=lost_replica)
        if self.spans is not None:
            # the cross-replica hop event: this rid's trace continues
            # on the survivor, attempt bumped (scheduler stamped it)
            self.spans.emit(_spans.REQUEUE, req.requeue_t, rid=req.rid,
                            replica=name, attempt=req.attempts,
                            lost_replica=lost_replica)

    # -------------------------------------------------------------- router
    def _replica_info(self, name: str) -> dict:
        """One replica's routing picture: direct host state (queue,
        slots, drain/degraded/pool flags — the same definitions
        ``health()`` reports, via the engine's shared properties) plus
        ONE registry snapshot for the SLO-burn and goodput gauges.
        Routing runs per admission, so it must not pay ``health()``'s
        full gauge-mirror pass on top."""
        eng = self.replicas[name]
        g = eng.stats.registry.snapshot()["gauges"]
        burn = 0.0
        for k, v in g.items():
            if k.startswith("Serve/slo_") and k.endswith("_burn") \
                    and isinstance(v, float) and not math.isnan(v):
                burn = max(burn, v)
        gp = g.get("Serve/goodput_frac")
        if not isinstance(gp, float) or math.isnan(gp):
            gp = 1.0
        queue_depth = eng.sched.queue_depth
        queue_full = bool(eng.cfg.max_queue
                          and queue_depth >= eng.cfg.max_queue)
        load = (queue_depth + eng.sched.occupancy
                + (1 if eng._prefill is not None else 0)) \
            / max(1, eng.cfg.slots)
        # "would I route here if anyone else could take it": healthy =
        # no exclusion reason holds. The reasons list IS the router's
        # explanation — the audit ring records it verbatim, so every
        # decision is explicable after the fact.
        reasons = []
        if eng.draining:
            reasons.append("draining")
        if queue_full:
            reasons.append("queue_full")
        if eng.degraded:
            reasons.append("degraded")
        if eng.pool_pressure:
            reasons.append("pool_pressure")
        if burn > 1.0:
            reasons.append("slo_burn")
        return {
            "name": name,
            "draining": eng.draining,
            "healthy": not reasons,
            "reasons": reasons,
            "load": load, "burn": burn, "goodput": gp,
        }

    def _ranked(self, role: str, exclude=(), admission: bool = True) \
            -> list:
        """Routing infos of ``role``'s replicas, best-first: healthy
        before unhealthy, then least-loaded, then lowest SLO burn, then
        highest goodput. ``admission=False`` keeps draining replicas in
        the pool (handoffs and requeues are backlog, which a drain must
        finish). Returns the info dicts so callers reuse ONE snapshot
        pass instead of re-reading registries per decision."""
        infos = [self._replica_info(n) for n in self.replicas
                 if self.roles[n] == role and n not in exclude]
        if admission:
            infos = [i for i in infos if not i["draining"]]
        infos.sort(key=lambda i: (0 if i["healthy"] else 1, i["load"],
                                  i["burn"], -i["goodput"], i["name"]))
        return infos

    def _stick(self, role: str, sid, name: str) -> None:
        key = (role, sid)
        self._session[key] = name
        self._session.move_to_end(key)
        if len(self._session) > self._session_cap:
            self._session.popitem(last=False)

    # --------------------------------------------------------- route audit
    def _audit_record(self, event: str, rid: Optional[int] = None,
                      role: Optional[str] = None, session_id=None,
                      tenant_id=None,
                      chosen: Optional[str] = None,
                      sticky: Optional[str] = None,
                      affinity: Optional[str] = None,
                      candidates=(), lost_replica: str = "") -> None:
        """One router decision into the bounded audit ring: the ranked
        candidates with their per-replica exclusion reasons (draining /
        queue_full / degraded / pool_pressure / slo_burn) — why the
        chosen replica won and why every other one didn't. No-op when
        tracing is disabled (the ring doesn't exist)."""
        if self._audit is None:
            return
        self._audit_seq += 1
        entry = {
            "seq": self._audit_seq, "t": self._clock(), "event": event,
            "rid": rid, "role": role, "session_id": session_id,
            "tenant_id": tenant_id,
            "chosen": chosen, "sticky": sticky, "affinity": affinity,
            "candidates": [
                {"name": i["name"], "healthy": i["healthy"],
                 "reasons": list(i["reasons"]),
                 "load": i["load"], "burn": i["burn"],
                 "goodput": i["goodput"],
                 # residency class when the router probed it (session
                 # routes): 0 tree hit / 1 host-tier hit / 2 cold
                 **({"residency": i["residency"]}
                    if "residency" in i else {})}
                for i in candidates],
        }
        if lost_replica:
            entry["lost_replica"] = lost_replica
        self._audit.append(entry)

    def route_audit(self, rid: Optional[int] = None) -> list:
        """The router decision audit: every route / shed /
        affinity-fallback / requeue still in the ring, oldest first —
        filtered to one request when ``rid`` is given. Each entry
        explains the decision: the ranked candidates with per-replica
        exclusion reasons. Empty when tracing is disabled."""
        if self._audit is None:
            return []
        entries = list(self._audit)
        if rid is None:
            return entries
        return [e for e in entries if e.get("rid") == rid]

    def _route(self, role: str, session_id=None, exclude=(),
               prompt=None) -> "tuple[str, dict]":
        """Pick the admission target; raises a typed shed when no
        replica of ``role`` is accepting (all draining/removed).
        Returns ``(name, decision)`` — the decision dict carries the
        ranked candidates and the affinity outcome so :meth:`submit`
        can write ONE audit entry once the rid exists.

        Session routing is RESIDENCY-ranked: among healthy candidates a
        replica whose radix tree holds the prompt's prefix ranks first,
        one whose HOST TIER holds it (evicted but demoted —
        serving/hostkv.py) ranks between tree hit and miss, policy
        (least-loaded) breaks the ties. The sticky replica still wins
        while healthy (it usually IS the tree hit); the ranking decides
        fallbacks and first routes, via read-only residency probes."""
        infos = self._ranked(role, exclude=exclude, admission=False)
        eligible = [i for i in infos if not i["draining"]]
        if not eligible:
            self.registry.counter("Fleet/sheds").inc()
            # the request never got a rid — the shed is still a routing
            # decision someone will ask about
            self._audit_record("shed", role=role, session_id=session_id,
                               candidates=infos)
            raise QueueFullError(
                f"no {role} replica accepting admissions (all draining); "
                "request shed")
        by_name = {i["name"]: i for i in eligible}
        choice = eligible[0]["name"]
        affinity = None
        sticky = None
        if session_id is not None:
            sticky = self._session.get((role, session_id))
            si = by_name.get(sticky) if sticky is not None else None
            sticky_ok = si is not None and si["healthy"]
            if not sticky_ok and prompt is not None:
                # no usable sticky replica: residency-rank the healthy
                # candidates (read-only probes — and ONLY on this
                # fallback/first-route path; a healthy sticky replica
                # wins below without paying the per-replica walks)
                healthy = [i for i in eligible if i["healthy"]]
                for i in healthy:
                    tb, hb = self.replicas[i["name"]] \
                        .prefix_residency(prompt)
                    # 0 = tree hit, 1 = host-tier hit, 2 = cold miss
                    i["residency"] = 0 if tb else (1 if hb else 2)
                if healthy:
                    best = min(healthy,
                               key=lambda i: (i["residency"], i["load"],
                                              i["burn"], -i["goodput"],
                                              i["name"]))
                    choice = best["name"]
            if sticky is not None:
                # stick when the sticky replica is routable AND healthy;
                # otherwise fall back to policy and record the miss (the
                # prefix will be rebuilt — or host-restored — at the new
                # home the residency ranking above picked)
                if sticky_ok:
                    choice = sticky
                if choice == sticky:
                    affinity = "hit"
                    self.registry.counter("Fleet/affinity_hits").inc()
                else:
                    affinity = "miss"
                    self.registry.counter("Fleet/affinity_misses").inc()
            self._stick(role, session_id, choice)
        return choice, {"role": role, "session_id": session_id,
                        "sticky": sticky, "affinity": affinity,
                        "candidates": infos}

    # -------------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               seed: int = 0, session_id=None, tenant_id=None,
               ttft_deadline_s: Optional[float] = None,
               total_deadline_s: Optional[float] = None) -> int:
        """Route one request into the fleet; returns its fleet-wide rid.
        Same contract as ``ServingEngine.submit`` plus ``session_id``
        (opaque, hashable): requests of one session prefer the replica
        holding their shared prefix. ``tenant_id`` rides along for
        per-tenant attribution (tenantscope). Raises the same typed
        :class:`QueueFullError` when every eligible replica sheds."""
        role = ROLE_PREFILL if self._disagg else ROLE_SERVE
        tried: set = set()
        last: Optional[QueueFullError] = None
        while True:
            try:
                name, decision = self._route(role, session_id=session_id,
                                             exclude=tried, prompt=prompt)
            except QueueFullError:
                if last is not None:
                    raise last
                raise
            eng = self.replicas[name]
            try:
                rid = eng.submit(prompt, max_new_tokens, seed=seed,
                                 ttft_deadline_s=ttft_deadline_s,
                                 total_deadline_s=total_deadline_s,
                                 session_id=session_id,
                                 tenant_id=tenant_id)
                break
            except QueueFullError as e:
                # this replica flipped to full/draining between the
                # health read and the submit: try the next-best before
                # shedding fleet-wide
                last = e
                tried.add(name)
        req = eng.sched.queue[-1]
        self._owner[rid] = name
        r = self.registry
        r.counter("Fleet/submitted").inc()
        r.counter(f"Fleet/routed_{name}").inc()
        # the decision becomes auditable the moment the rid exists; an
        # affinity fallback is its own event kind so dashboards can
        # count prefix-locality losses without parsing candidates
        self._audit_record(
            "affinity_fallback" if decision["affinity"] == "miss"
            else "route",
            rid=rid, chosen=name, tenant_id=tenant_id, **decision)
        if self.spans is not None:
            # the trace context's first fleet hop: rid → replica. The
            # replica's own ring continues from its queue span.
            self.spans.emit(_spans.ROUTE, req.submit_t, rid=rid,
                            replica=name)
        if self.capture is not None:
            self.capture.on_submit(req, session_id=session_id,
                                   ttft_deadline_s=ttft_deadline_s,
                                   total_deadline_s=total_deadline_s)
        return rid

    def cancel(self, rid: int) -> Optional[Request]:
        """Cancel wherever the request lives — its owning replica or the
        pending-handoff buffer."""
        for i, (req, _payload) in enumerate(self._handoffs):
            if req.rid == rid:
                del self._handoffs[i]
                self.registry.gauge("Fleet/handoff_pending").set(
                    len(self._handoffs))
                req.status = RequestStatus.CANCELLED
                req.error = "cancelled during prefill→decode handoff"
                req.finish_t = self._clock()
                self._adopt_result(req, self._owner.get(rid, ""))
                return req
        name = self._owner.get(rid)
        if name in self.replicas:
            req = self.replicas[name].cancel(rid)
            if req is not None:
                self.replicas[name].pop_result(rid)
                self._adopt_result(req, name)
            return req
        return None

    # ------------------------------------------------------------- serving
    def step(self) -> list:
        """One fleet iteration: chaos hook, pending handoffs, then one
        ``step()`` on every replica. Returns every request that retired
        anywhere in the fleet this iteration; results are also held in
        the fleet's own bounded store for :meth:`pop_result`."""
        out: list = []
        if self.chaos is not None:
            # only offer LEGALLY removable victims (never the last
            # replica, never the last of a disaggregated role) — a
            # chaos fault must inject failure, not crash the router
            victim = self.chaos.maybe_kill(self._killable())
            if victim is not None:
                self.kill_replica(victim)
        if self._handoffs:
            self._pump_handoffs()
        for name in list(self.replicas):
            eng = self.replicas[name]
            for req in eng.step():
                eng.pop_result(req.rid)
                self._adopt_result(req, name)
                out.append(req)
        if self.autoscaler is not None:
            # after the replica loop (safe to mutate the replicas dict)
            # and before the inline-retire drain, so anything a removal
            # sheds rides THIS step's return
            self.autoscaler.on_step()
        if self._retired_inline:
            # retirements the fleet layer itself produced (handoff
            # timeouts, requeue sheds) ride the same return channel
            out.extend(self._retired_inline)
            self._retired_inline = []
        if self._tracing:
            for req in out:
                self._observe_hops(req)
        self._iterations += 1
        self.registry.counter("Fleet/iterations").inc()
        return out

    def _observe_hops(self, req: Request) -> None:
        """One retired request's hop decomposition into the
        ``Fleet/hop_*`` histograms (p50/p99 per hop across the fleet —
        the aggregate view of :meth:`request_trace`). Null hops (e.g.
        handoff on a uniform fleet) are skipped, not recorded as 0."""
        tr = hop_trace(req)
        r = self.registry
        for h in HOP_NAMES + ("e2e",):
            v = tr.get(f"{h}_s")
            if v is not None:
                r.histogram(f"Fleet/hop_{h}_s").observe(v)

    def _killable(self) -> list:
        """Replica names whose removal :meth:`_remove` would accept."""
        if len(self.replicas) <= 1:
            return []
        if not self._disagg:
            return list(self.replicas)
        counts: dict = {}
        for n in self.replicas:
            counts[self.roles[n]] = counts.get(self.roles[n], 0) + 1
        return [n for n in self.replicas if counts[self.roles[n]] > 1]

    def _on_prefill_placed(self, name: str, req: Request,
                           slot: int) -> None:
        """The disaggregation seam (``ServingEngine.on_placed``): a
        prefill replica just seated a finished prefill — export its
        pages to host, release the slot (the prompt's blocks stay in the
        source tree for future sharing), queue the handoff. The takeover
        happens via these side effects; the hook returns nothing."""
        eng = self.replicas[name]
        t0 = self._clock()
        payload = eng.export_request(req)
        eng.release_request(req)
        # export stamp is unconditional (two host clock reads): hop_trace
        # needs it to tell "died waiting for a decode slot" apart from
        # "decoded" even when tracing is off
        req.export_t = self._clock()
        if self.spans is not None:
            # the export hop: pages gathered to host on the source
            # replica — the first fleet-side leg of this rid's trace
            self.spans.emit(_spans.HANDOFF_EXPORT, t0, req.export_t,
                            rid=req.rid, replica=name,
                            **({"attempt": req.attempts}
                               if req.attempts else {}))
        self._handoffs.append((req, payload))
        self.registry.counter("Fleet/handoffs").inc()
        self.registry.gauge("Fleet/handoff_pending").set(
            len(self._handoffs))

    def _pump_handoffs(self) -> None:
        """Try to land every pending handoff on a decode replica:
        affinity-aware, best-ranked first, and a destination that cannot
        take it right now (no free slot / pool pressure) just leaves the
        payload host-held for the next iteration. Expired deadlines
        retire here — a handed-off request is in no scheduler's sweep.
        The ranking snapshot is taken ONCE per pump and refreshed only
        after a successful import changes a replica's load — not per
        pending request (handoffs pile up exactly when this loop runs
        hottest)."""
        remaining = []

        def _targets() -> list:
            # prefer replicas still accepting intake: an import onto a
            # DRAINING decode replica gives it new work exactly when a
            # scale-down is waiting for it to idle. Fall back to the
            # draining pool only when EVERY decode replica drains
            # (fleet-wide drain: handoffs are backlog and must finish).
            infos = self._ranked(ROLE_DECODE, admission=False)
            open_ = [i["name"] for i in infos if not i["draining"]]
            return open_ if open_ else [i["name"] for i in infos]

        ranked = _targets()
        for req, payload in self._handoffs:
            now = self._clock()
            if req.deadline_total is not None and now >= req.deadline_total:
                req.status = RequestStatus.TIMEOUT
                req.error = "total deadline expired during handoff"
                req.finish_t = now
                self.registry.counter("Fleet/handoff_timeouts").inc()
                if self.spans is not None:
                    self.spans.emit(_spans.MARKER, now,
                                    name="handoff_timeout", rid=req.rid)
                self._adopt_result(req, self._owner.get(req.rid, ""))
                self._retired_inline.append(req)
                continue
            order = list(ranked)
            sticky = (self._session.get((ROLE_DECODE, req.session_id))
                      if req.session_id is not None else None)
            if sticky in order:
                order.remove(sticky)
                order.insert(0, sticky)
            placed = False
            for name in order:
                if self.replicas[name].import_request(req, payload):
                    self._owner[req.rid] = name
                    if req.session_id is not None:
                        self._stick(ROLE_DECODE, req.session_id, name)
                    self.registry.counter("Fleet/handoff_imports").inc()
                    if self.spans is not None:
                        # the pending + import hops: host-held wait,
                        # then the scatter into the decode replica (the
                        # engine stamped import_t0/t1 on the request)
                        att = ({"attempt": req.attempts}
                               if req.attempts else {})
                        if req.export_t is not None \
                                and req.import_t0 is not None:
                            self.spans.emit(_spans.HANDOFF_PENDING,
                                            req.export_t,
                                            req.import_t0, rid=req.rid,
                                            **att)
                        if req.import_t0 is not None \
                                and req.import_t1 is not None:
                            self.spans.emit(_spans.HANDOFF_IMPORT,
                                            req.import_t0, req.import_t1,
                                            rid=req.rid, replica=name,
                                            **att)
                    placed = True
                    ranked = _targets()
                    break
            if not placed:
                remaining.append((req, payload))
        self._handoffs = remaining
        self.registry.gauge("Fleet/handoff_pending").set(
            len(self._handoffs))

    def _adopt_result(self, req: Request, name: str) -> None:
        if self.capture is not None:
            # every terminal path funnels through adoption; the capture
            # dedupes by rid, so late re-visits (loss harvest) are safe
            self.capture.on_result(req)
        self.results[req.rid] = req
        if name:
            self._owner[req.rid] = name
        if len(self.results) > self._max_results:
            old_rid, _old = self.results.popitem(last=False)
            owner = self._owner.pop(old_rid, None)
            rep = self.replicas.get(owner)
            if rep is not None:
                # the eviction is attributed to the replica that served
                # the request — its Serve/results_evicted counter is the
                # one dashboards already watch
                rep.stats.on_results_evicted()
            self.registry.counter("Fleet/results_evicted").inc()
            warning_once(
                f"fleet results store hit its cap ({self._max_results}); "
                "evicting oldest finished requests — collect results via "
                "step()'s return value or pop_result()")

    def pop_result(self, rid: int) -> Optional[Request]:
        """Collect (and release) a finished request by rid, regardless
        of which replica retired it — routed by rid through the owner
        map, never a scan."""
        req = self.results.pop(rid, None)
        if req is None:
            name = self._owner.get(rid)
            if name in self.replicas:
                req = self.replicas[name].pop_result(rid)
        if req is not None:
            self._owner.pop(rid, None)
        return req

    # ---------------------------------------------------------- lifecycle
    def begin_drain(self) -> None:
        """Fleet-wide drain: every replica stops admitting (new submits
        shed typed); queued, running, and handed-off requests finish."""
        self._draining = True
        for eng in self.replicas.values():
            eng.begin_drain()
        if self.capture is not None:
            self.capture.on_chaos("begin_drain")

    def end_drain(self) -> None:
        self._draining = False
        for eng in self.replicas.values():
            eng.end_drain()
        if self.capture is not None:
            self.capture.on_chaos("end_drain")

    def begin_drain_replica(self, name: str) -> None:
        """Drain ONE replica (the scale-down prelude): its intake
        closes — the router stops admitting to it, handoffs route to
        its siblings — while its queued/running backlog finishes.
        Recorded as a replica-scoped chaos event so an autoscaled run
        replays its drain edges deterministically."""
        if name not in self.replicas:
            raise KeyError(f"no replica named {name!r} "
                           f"(have {list(self.replicas)})")
        self.replicas[name].begin_drain()
        self.registry.counter("Fleet/replica_drains").inc()
        if self.capture is not None:
            self.capture.on_chaos("begin_drain", name)

    def end_drain_replica(self, name: str) -> None:
        """Reopen one replica's intake (drain aborted: load reversed,
        or an operator changed their mind). No-op on a fleet-wide
        drain — that outranks per-replica state."""
        if name not in self.replicas:
            raise KeyError(f"no replica named {name!r} "
                           f"(have {list(self.replicas)})")
        if self._draining:
            return
        self.replicas[name].end_drain()
        if self.capture is not None:
            self.capture.on_chaos("end_drain", name)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def idle(self) -> bool:
        return (not self._handoffs
                and all(e.sched.idle and e._prefill is None
                        for e in self.replicas.values()))

    def drain(self, max_iterations: int = 1_000_000) -> dict:
        """Graceful fleet shutdown: drain mode, run until every replica
        is idle and no handoff is pending, return the fleet results."""
        self.begin_drain()
        it = 0
        while not self.idle:
            self.step()
            it += 1
            if it > max_iterations:
                raise RuntimeError(
                    f"fleet failed to drain in {max_iterations} "
                    "iterations — scheduler wedged?")
        return self.results

    def serve_batch(self, prompts, max_new_tokens=None, seeds=None,
                    session_ids=None, tenant_ids=None) -> list:
        """Convenience mirror of ``ServingEngine.serve_batch`` across the
        fleet: submit, drive, return each request's tokens in submission
        order (results popped)."""
        import numpy as np

        from .engine import expand_per_request

        n = len(prompts)
        mn = expand_per_request(max_new_tokens, n, None, int)
        sd = expand_per_request(seeds, n, 0, int)
        sid = expand_per_request(session_ids, n, None)
        tid = expand_per_request(tenant_ids, n, None)
        rids = [self.submit(p, mn[i], seed=sd[i], session_id=sid[i],
                            tenant_id=tid[i])
                for i, p in enumerate(prompts)]
        want = set(rids)
        got: dict = {}
        it = 0
        while len(got) < n:
            for req in self.step():
                if req.rid in want:
                    got[req.rid] = req
                    self.results.pop(req.rid, None)
                    self._owner.pop(req.rid, None)
            it += 1
            if it > 1_000_000:
                raise RuntimeError("fleet serve_batch failed to finish — "
                                   "scheduler wedged?")
        return [np.asarray(got[r].tokens, np.int32) for r in rids]

    # ------------------------------------------------------------- readout
    def health(self) -> dict:
        """Fleet liveness/readiness rollup + per-replica snapshots,
        mirrored to ``Fleet/*`` gauges (replicas/ready/queue/occupancy/
        handoffs) so the scrape surface carries the router's picture."""
        per = {name: eng.health() for name, eng in self.replicas.items()}
        ready = sum(1 for h in per.values() if h["ready"])
        out = {
            "replicas": len(per),
            "ready_replicas": ready,
            "ready": ready > 0 and not self._draining,
            "state": "draining" if self._draining else "serving",
            "queue_depth": sum(h["queue_depth"] for h in per.values()),
            "occupancy": sum(h["occupancy"] for h in per.values()),
            "handoff_pending": len(self._handoffs),
            "iterations": self._iterations,
            "roles": dict(self.roles),
            "per_replica": per,
        }
        self.registry.set_gauges({
            "Fleet/replicas": float(out["replicas"]),
            "Fleet/replicas_ready": float(ready),
            "Fleet/ready": float(out["ready"]),
            "Fleet/queue_depth": float(out["queue_depth"]),
            "Fleet/occupancy": float(out["occupancy"]),
            "Fleet/handoff_pending": float(len(self._handoffs)),
        })
        return out

    def _on_regret_resume(self, name: str, session_id, tokens: int) \
            -> None:
        """A replica's kvscope reported a regretted resume (the session
        came back and re-paid ghost-covered prefill there). Fleet-wide
        it always counts; when the session was STICKY to that very
        replica it is an affinity regret — the router sent the session
        home for its prefix, and home had evicted it. That is the
        failure a host KV tier (or smarter eviction) removes."""
        r = self.registry
        r.counter("Fleet/resume_regrets").inc()
        r.counter("Fleet/resume_regret_tokens").inc(tokens)
        role = ROLE_PREFILL if self._disagg else ROLE_SERVE
        if self._session.get((role, session_id)) == name:
            r.counter("Fleet/affinity_regret").inc()
            r.counter("Fleet/affinity_regret_tokens").inc(tokens)

    def kv_residency(self) -> Optional[dict]:
        """Fleet-wide KV residency rollup: every replica's kvscope
        snapshot plus the affinity-aware regret counters only the
        router can attribute. None when no replica runs the observatory
        (``serving.kvscope`` off)."""
        per = {}
        for n, e in self.replicas.items():
            if e.kvscope is None:
                continue
            s = e.kvscope.snapshot()
            if e.hostkv is not None:
                s["host_tier"] = e.hostkv.snapshot()
            if e.nvmekv is not None:
                s["nvme_tier"] = e.nvmekv.snapshot()
            per[n] = s
        if not per:
            return None
        c = self.registry.snapshot()["counters"]
        totals = {
            "regret_tokens": sum((s["regret"]["regret_tokens"])
                                 for s in per.values()),
            "prefill_tokens_paid": sum(s["regret"]["prefill_tokens_paid"]
                                       for s in per.values()),
            "sessions_resumed": sum(s["sessions"]["resumed"]
                                    for s in per.values()),
            "regret_resumes": sum(s["sessions"]["regret_resumes"]
                                  for s in per.values()),
            "host_restored_resumes": sum(
                s["sessions"].get("host_restored_resumes", 0)
                for s in per.values()),
            "host_tier_restores": sum(
                (s.get("host_tier") or {}).get("restores", 0)
                for s in per.values()),
            "host_tier_bytes": sum(
                (s.get("host_tier") or {}).get("bytes", 0)
                for s in per.values()),
            # the disk rung, rolled up beside the DRAM rung: verified
            # promotions (blocks read back), resident bytes, and the
            # fallbacks/aio-errors ops gates on fleet-wide
            "nvme_tier_promotions": sum(
                (s.get("nvme_tier") or {}).get("promotions", 0)
                for s in per.values()),
            "nvme_tier_bytes": sum(
                (s.get("nvme_tier") or {}).get("bytes", 0)
                for s in per.values()),
            "nvme_tier_fallbacks": sum(
                (s.get("nvme_tier") or {}).get("fallbacks", 0)
                for s in per.values()),
            "nvme_aio_errors": sum(
                (s.get("nvme_tier") or {}).get("aio_errors", 0)
                for s in per.values()),
        }
        totals["regret_frac"] = (
            totals["regret_tokens"] / totals["prefill_tokens_paid"]
            if totals["prefill_tokens_paid"] else 0.0)
        return {
            "replicas": per,
            "totals": totals,
            "fleet": {
                "resume_regrets": int(c.get("Fleet/resume_regrets", 0)),
                "resume_regret_tokens": int(
                    c.get("Fleet/resume_regret_tokens", 0)),
                "affinity_regret": int(c.get("Fleet/affinity_regret", 0)),
                "affinity_regret_tokens": int(
                    c.get("Fleet/affinity_regret_tokens", 0)),
            },
        }

    def fleet_goodput(self) -> Optional[dict]:
        """The PR-8 rollup math over per-replica goodput ledgers
        (wall-weighted fraction, summed buckets), exported as
        ``Fleet/goodput_*`` gauges. None when no replica has a ledger
        (``serving.goodput`` off).

        With self-speculative decoding on anywhere in the fleet, the
        rollup also carries the fleet-wide accepted-tokens-per-step
        multiple (summed emitted tokens over summed slot-steps across
        replicas running the lane) — the decode-throughput multiplier
        the goodput fraction alone cannot see, since a verify step is
        one productive iteration whether it commits 1 token or 5."""
        from ..observability.goodput import rollup_goodput

        snaps = [eng.goodput.snapshot() for eng in self.replicas.values()
                 if eng.goodput is not None]
        spec = [s for s in (eng.spec_snapshot()
                            for eng in self.replicas.values())
                if s is not None]
        if not snaps and not spec:
            return None
        roll = rollup_goodput(snaps) if snaps else {
            "wall_s": 0.0, "productive_s": 0.0, "badput_total_s": 0.0,
            "goodput_frac": None}
        gauges = {"Fleet/goodput_wall_s": roll["wall_s"],
                  "Fleet/goodput_productive_s": roll["productive_s"],
                  "Fleet/goodput_badput_total_s": roll["badput_total_s"]}
        if roll["goodput_frac"] is not None:
            gauges["Fleet/goodput_frac"] = roll["goodput_frac"]
        if spec:
            steps = sum(s["slot_steps"] for s in spec)
            emitted = sum(s["emitted_tokens"] for s in spec)
            roll["speculation"] = {
                "replicas": len(spec),
                "slot_steps": steps,
                "emitted_tokens": emitted,
                "accepted_tokens": sum(s["accepted_tokens"] for s in spec),
                "proposed_tokens": sum(s["proposed_tokens"] for s in spec),
                "accepted_tokens_per_step":
                    (emitted / steps) if steps else None,
            }
            if steps:
                gauges["Fleet/spec_accepted_tokens_per_step"] = \
                    emitted / steps
        self.registry.set_gauges(gauges)
        return roll

    def scaling_report(self) -> Optional[dict]:
        """Fleet-wide arrival & scaling rollup over per-replica loadscope
        snapshots (``observability/loadscope.py``): summed offered load,
        the bottleneck utilization, the nearest SLO time-to-violation,
        and the scaling what-ifs — add_replica / remove_replica / the
        prefill↔decode rebalance a disaggregated fleet can make —
        scored at fleet size. Exported as ``Fleet/arrival_*`` /
        ``Fleet/utilization_max`` / ``Fleet/slo_ttv_min_s`` gauges.
        None when no replica runs the observatory (``serving.loadscope``
        off); per-replica unmeasured inputs degrade the dependent
        aggregates to None, never raise."""
        from ..observability.loadscope import (SCALING_SCHEMA,
                                               score_what_ifs)

        per = {}
        for n, e in self.replicas.items():
            if getattr(e, "loadscope", None) is None:
                continue
            snap = e.scaling_snapshot()
            if snap is not None:
                per[n] = snap

        if not per:
            return None

        def _vals(section, key):
            vs = [(s.get(section) or {}).get(key) for s in per.values()]
            return [v for v in vs if v is not None]

        rates = _vals("arrival", "rate_per_s")
        offered = _vals("arrival", "offered_tokens_per_s")
        off_dec = _vals("arrival", "decode_tokens_per_s")
        off_pre = _vals("arrival", "prompt_tokens_per_s")
        serviceable = _vals("service", "serviceable_decode_tokens_per_s")
        svc_pre = _vals("service", "prefill_tokens_per_s")
        rhos = _vals("utilization", "rho")
        cvs = _vals("arrival", "interarrival_cv")
        svc_means = _vals("utilization", "mean_service_s")
        ttvs = _vals("forecast", "slo_ttv_s")

        offered_total = sum(offered) if offered else None
        serviceable_total = sum(serviceable) if serviceable else None
        # fleet ρ is PER PHASE over the measured replicas only (honest
        # when some replica's spans are off — its load is also
        # excluded), then the bottleneck max: decode demand over decode
        # capacity, prompt demand over prefill capacity
        rho_dec_fleet = (sum(off_dec) / serviceable_total
                         if off_dec and serviceable_total else None)
        rho_pre_fleet = (sum(off_pre) / sum(svc_pre)
                         if off_pre and svc_pre and sum(svc_pre) > 0
                         else None)
        rho_fleet = (max(v for v in (rho_dec_fleet, rho_pre_fleet)
                         if v is not None)
                     if rho_dec_fleet is not None
                     or rho_pre_fleet is not None else None)
        rho_prefill = rho_decode = None
        pr_count = sum(1 for r in self.roles.values()
                       if r == ROLE_PREFILL)
        if self._disagg:
            pre = [(per[n].get("utilization") or {}).get("rho")
                   for n in per if self.roles.get(n) == ROLE_PREFILL]
            dec = [(per[n].get("utilization") or {}).get("rho")
                   for n in per if self.roles.get(n) == ROLE_DECODE]
            pre = [v for v in pre if v is not None]
            dec = [v for v in dec if v is not None]
            rho_prefill = max(pre) if pre else None
            rho_decode = max(dec) if dec else None

        slots = next(iter(self.replicas.values())).cfg.slots
        cfg0 = next(iter(per.values()))
        rho_high = ((cfg0.get("utilization") or {}).get("rho_high")
                    or 0.85)
        what_ifs = score_what_ifs(
            rho=rho_fleet if rho_fleet is not None
            else (max(rhos) if rhos else None),
            replicas=len(self.replicas), slots=slots,
            mean_service_s=(sum(svc_means) / len(svc_means)
                            if svc_means else None),
            arrival_cv=(sum(cvs) / len(cvs) if cvs else None),
            rho_high=rho_high, rho_prefill=rho_prefill,
            rho_decode=rho_decode, prefill_replicas=pr_count)

        gauges = {}
        if rates:
            gauges["Fleet/arrival_rate_per_s"] = sum(rates)
        if offered_total is not None:
            gauges["Fleet/offered_tokens_per_s"] = offered_total
        if rhos:
            gauges["Fleet/utilization_max"] = max(rhos)
        if ttvs:
            gauges["Fleet/slo_ttv_min_s"] = min(ttvs)
        self.registry.set_gauges(gauges)

        return {
            "schema": SCALING_SCHEMA,
            "replicas": per,
            "fleet": {
                "replica_count": len(self.replicas),
                "prefill_replicas": pr_count,
                "arrival_rate_per_s": sum(rates) if rates else None,
                "offered_tokens_per_s": offered_total,
                "serviceable_tokens_per_s": serviceable_total,
                "rho": rho_fleet,
                "rho_prefill": (rho_prefill if self._disagg
                                else rho_pre_fleet),
                "rho_decode": (rho_decode if self._disagg
                               else rho_dec_fleet),
                "utilization_max": max(rhos) if rhos else None,
                "slo_ttv_min_s": min(ttvs) if ttvs else None,
            },
            "what_ifs": what_ifs,
        }

    def metrics_snapshot(self) -> dict:
        # refresh the derived gauges FIRST (publish_metrics order) so
        # the "fleet" section carries current health/goodput, not the
        # previous call's
        self.health()
        gp = self.fleet_goodput()
        sc = self.scaling_report()
        snap = self.registry.snapshot()
        out = {
            "iterations": self._iterations,
            "fleet": {**snap["counters"], **snap["gauges"]},
            "replicas": {name: {"role": self.roles[name],
                                "compiles": eng.compiles,
                                **eng.stats.snapshot()}
                         for name, eng in self.replicas.items()},
        }
        if gp is not None:
            out["goodput"] = gp
        if sc is not None:
            out["scaling"] = sc
        return out

    def requests_table(self) -> list:
        """Fleet-wide in-flight table: every replica's rows plus the
        pending-handoff residents, each labeled with its replica."""
        rows = []
        for name, eng in self.replicas.items():
            for row in eng.requests_table():
                row["replica"] = name
                rows.append(row)
        for req, _payload in self._handoffs:
            rows.append({"rid": req.rid, "state": "handoff", "slot": None,
                         "prompt_len": req.prompt_len,
                         "max_new": req.max_new,
                         "tokens": len(req.tokens),
                         "submit_t": req.submit_t, "admit_t": req.admit_t,
                         "deadline_ttft": req.deadline_ttft,
                         "deadline_total": req.deadline_total,
                         "status": req.status.value,
                         "attempts": req.attempts,
                         "trace": hop_trace(req),
                         # the SOURCE replica that produced the payload:
                         # a stuck handoff must be attributable
                         "replica": self._owner.get(req.rid)})
        return rows

    # ------------------------------------------------- distributed tracing
    def request_trace(self, rid: int) -> Optional[dict]:
        """One request's end-to-end hop-latency decomposition
        (``queue_wait/prefill/handoff_wait/import/decode/e2e`` — see
        :func:`~..observability.export.hop_trace`), wherever the request
        currently lives: the fleet results store, the pending-handoff
        buffer, or its owning replica (results or live). The non-null
        hops of a completed request tile ``[submit, finish]`` — their
        sum IS the e2e wall (the documented invariant, pinned on the
        fake clock). Works with tracing disabled — the hops come from
        host timestamps on the request, not from any span ring. None
        for an unknown (or evicted) rid."""
        owner = self._owner.get(rid)
        req = self.results.get(rid)
        state = None
        if req is None:
            for r, _payload in self._handoffs:
                if r.rid == rid:
                    req, state = r, "handoff"
                    break
        if req is not None:
            out = {"rid": rid, "status": req.status.value,
                   "finished": req.finished, "slot": req.slot,
                   "tokens": len(req.tokens), "hops": hop_trace(req)}
            if state is not None:
                out["state"] = state
        else:
            if owner not in self.replicas:
                return None
            out = self.replicas[owner].request_trace(rid)
            if out is None:
                return None
        out["replica"] = owner
        return out

    def merge_trace(self, job_name: str = "fleet") -> dict:
        """ONE Chrome/Perfetto trace for the whole fleet: every live
        replica's span ring under its own pid, the fleet ring (router
        decisions, handoff hops) under a ``router`` pid, and each
        cross-replica request stitched into a flow — see
        :func:`~..observability.export.merge_fleet_trace`. Empty when
        tracing is disabled (no rings exist)."""
        from ..observability.export import merge_fleet_trace

        rings = {n: e.spans.events() for n, e in self.replicas.items()
                 if e.spans is not None}
        return merge_fleet_trace(
            rings,
            self.spans.events() if self.spans is not None else None,
            job_name=job_name)

    # ----------------------------------------------------------- incidents
    def _incident_redirect(self, name: str, reason: str) \
            -> Optional[Path]:
        """The per-replica flight-recorder redirect hook: replica
        ``name`` is about to dump for ``reason``. The first trigger
        opens a shared incident (fanning the dump out to every
        sibling); a sibling asked to dump DURING the fan-out — or a
        second trigger within the same fleet iteration (one event,
        several tripwires) — gets the existing incident's per-replica
        subdirectory instead of opening a duplicate."""
        with self._incident_lock:
            if self._incident_open is not None:
                return self._incident_open / name
            last = self._incident_last
            if last is not None and last[1] == self._iterations:
                # join: this iteration's incident already captured the
                # fleet (this replica's fan-out dump included); a second
                # dump from the same replica lands beside it suffixed
                return last[0] / name
            d = self._open_incident(reason, trigger=name)
            return None if d is None else d / name

    def dump_incident(self, reason: str = "manual") -> Optional[Path]:
        """Correlated capture NOW: every replica's flight recorder dumps
        into one shared incident directory, alongside the fleet's own
        artifacts (router/handoff ring, route audit, merged trace).
        Returns the incident directory, or None when no replica carries
        a flight recorder (``serving.flight_dir`` unset)."""
        with self._incident_lock:
            if self._incident_open is not None:
                return self._incident_open
            return self._open_incident(reason, trigger=None)

    def _open_incident(self, reason: str,
                       trigger: Optional[str]) -> Optional[Path]:
        """Create ``<flight_dir>/incident_<stamp>_<reason>`` and fan the
        capture out: every replica except ``trigger`` (whose own dump is
        already in flight, redirected here) dumps into its subdirectory;
        the fleet writes ``incident.json`` (the shared incident id +
        which replicas were live), its ring, the route audit, and the
        merged cross-replica trace under ``fleet/``. Caller holds
        ``_incident_lock``."""
        from ..observability.flight import sanitize_reason, unique_dir

        if self._incident_base is None:
            return None
        stamp = time.strftime("%Y%m%d-%H%M%S")
        safe = sanitize_reason(reason, fallback="incident")
        d = unique_dir(self._incident_base / f"incident_{stamp}_{safe}")
        try:
            d.mkdir(parents=True)
        except OSError as e:
            log_dist(f"fleet incident capture: cannot create {d} "
                     f"({e!r})", ranks=[0], level="WARNING")
            return None
        self._incidents += 1
        self.registry.counter("Fleet/incidents").inc()
        if self.spans is not None:
            self.spans.emit(_spans.MARKER, self._clock(), name="incident",
                            reason=reason, incident=d.name,
                            trigger=trigger or "")
        self._incident_open = d
        dumped = []
        try:
            for n, eng in self.replicas.items():
                if n == trigger:
                    dumped.append(n)   # its dump is in flight, into d/n
                    continue
                if eng.flight is not None \
                        and eng.flight.dump(f"incident {reason}",
                                            into=d / n) is not None:
                    dumped.append(n)
            self._write_incident_artifacts(d, reason, trigger, dumped)
        finally:
            self._incident_open = None
            self._incident_last = (d, self._iterations)
        log_dist(f"fleet incident capture: {len(dumped)}/"
                 f"{len(self.replicas)} replicas dumped to {d} "
                 f"(reason: {reason})", ranks=[0], level="WARNING")
        return d

    def _write_incident_artifacts(self, d: Path, reason: str,
                                  trigger: Optional[str],
                                  dumped: list) -> None:
        """The fleet's half of an incident dir. Per-artifact write
        guards, like the flight recorder's: incident capture runs on
        failure paths — one bad artifact must not lose the rest."""
        from ..observability.flight import _json_default

        fd = d / "fleet"

        def _w(name, write):
            try:
                write()
            except Exception as e:
                try:
                    (d / (name + ".error")).write_text(repr(e),
                                                       encoding="utf-8")
                except OSError:
                    pass

        def _w_manifest():
            (d / "incident.json").write_text(json.dumps({
                "incident_id": d.name, "reason": reason,
                "trigger_replica": trigger,
                "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "clock_now": self._clock(),
                "replicas_live": len(self.replicas),
                "replicas": list(self.replicas),
                "roles": dict(self.roles),
                "dumped": dumped,
                "handoff_pending": len(self._handoffs),
            }, indent=2, default=str), encoding="utf-8")

        def _w_fleet_events():
            fd.mkdir(exist_ok=True)
            with open(fd / "events.jsonl", "w", encoding="utf-8") as f:
                for ev in self.spans.events():
                    f.write(json.dumps(ev.as_dict(),
                                       separators=(",", ":"),
                                       default=_json_default) + "\n")

        def _w_audit():
            fd.mkdir(exist_ok=True)
            with open(fd / "route_audit.jsonl", "w",
                      encoding="utf-8") as f:
                for entry in self.route_audit():
                    f.write(json.dumps(entry, separators=(",", ":"),
                                       default=str) + "\n")

        def _w_trace():
            fd.mkdir(exist_ok=True)
            (fd / "trace_merged.json").write_text(
                json.dumps(self.merge_trace(), default=_json_default),
                encoding="utf-8")

        def _w_capture():
            fd.mkdir(exist_ok=True)
            (fd / "traffic_trace.jsonl").write_text(
                self.capture.tail_text(), encoding="utf-8")

        def _w_autoscale():
            fd.mkdir(exist_ok=True)
            (fd / "autoscale_audit.jsonl").write_text(
                self.autoscaler.audit_jsonl(), encoding="utf-8")

        _w("incident.json", _w_manifest)
        if self.spans is not None:
            _w("events.jsonl", _w_fleet_events)
            _w("route_audit.jsonl", _w_audit)
            _w("trace_merged.json", _w_trace)
        if self.capture is not None:
            # the capture ring's tail: the incident is replayable
            # standing alone (docs/OPERATIONS.md incident-replay runbook)
            _w("traffic_trace.jsonl", _w_capture)
        if self.autoscaler is not None:
            # the decision ring: WHY the fleet was the size it was when
            # the incident hit (docs/OPERATIONS.md autoscaler runbook)
            _w("autoscale_audit.jsonl", _w_autoscale)

    def publish_metrics(self, monitor, step: Optional[int] = None) -> int:
        """Push ``Fleet/*`` (health rollup + goodput refreshed first)
        through a monitor fan-out, same contract as the engines'."""
        from ..observability.metrics import publish_registry

        self.health()
        self.fleet_goodput()
        return publish_registry(self.registry, monitor, step,
                                default_step_counter="Fleet/iterations")

    def autoscale_audit(self) -> list:
        """The autoscaler's decision ring (oldest first, plain dicts);
        empty when no autoscaler is attached."""
        if self.autoscaler is None:
            return []
        return self.autoscaler.audit_entries()

    def serve_telemetry(self, host: str = "127.0.0.1", port: int = 0,
                        token: str = "") -> int:
        """Start the FLEET's ops surface (the router's view — distinct
        from any per-replica server): ``/metrics`` (``Fleet/*``),
        ``/healthz``-``/readyz`` (the health rollup), ``/scaling`` (the
        fleet scaling report), and — when the autoscaler is on —
        ``GET /autoscale`` (status + decision audit tail) and the
        token-gated ``POST /autoscale`` freeze/pin override. Returns
        the bound port; idempotent while running."""
        from ..observability.server import TelemetryHooks, TelemetryServer

        if getattr(self, "telemetry", None) is not None:
            return self.telemetry.port
        reg = self.registry

        def refresh():
            self.health()
            self.fleet_goodput()

        asc = self.autoscaler
        hooks = TelemetryHooks(
            registry=reg,
            step_fn=lambda: int(reg.counter("Fleet/iterations").value),
            refresh_fn=refresh,
            health_fn=self.health,
            scaling_fn=self.scaling_report,
            dump_fn=((lambda: self.dump_incident("manual"))
                     if self._incident_base is not None else None),
            autoscale_fn=(asc.status if asc is not None else None),
            autoscale_control_fn=(asc.control if asc is not None
                                  else None))
        server = TelemetryServer(hooks, host=host, port=port, token=token)
        bound = server.start()
        self.telemetry = server
        return bound

    def close(self) -> None:
        """Teardown every replica (telemetry listeners etc.) and the
        fleet's own telemetry server; the fleet object is not reusable
        afterwards."""
        if getattr(self, "telemetry", None) is not None:
            self.telemetry.close()
            self.telemetry = None
        for eng in self.replicas.values():
            eng.close()
