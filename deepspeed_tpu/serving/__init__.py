"""Continuous-batching serving layer (reference: DeepSpeed-MII / FastGen).

Built on the split ``prefill_tokens``/``decode_tokens`` programs: a
slot-based persistent KV cache (``slots.py``), a host-side scheduler with
chunked SplitFuse-style prefill (``scheduler.py``), and a
``submit()/step()/drain()`` engine whose steady state reuses a bounded,
shape-bucketed compiled-program set (``engine.py``). Outputs are
bit-identical to single-request ``generate()`` with the same request seed
— see docs/SERVING.md.
"""

from ..resilience.guards import PagePoolExhausted, QueueFullError, \
    RequestStatus
from .autoscaler import AutoscaleConfig, AutoscaleDecision, Autoscaler
from .engine import ServingEngine
from .fleet import FleetEngine
from .hostkv import HostKVTier
from .pages import (PagePool, RadixPrefixTree, export_slot, import_slot,
                    init_paged_slots)
from .scheduler import ChunkPlan, Request, Scheduler, plan_chunks
from .slots import init_slots, insert_request

__all__ = ["ServingEngine", "FleetEngine", "Scheduler", "Request",
           "ChunkPlan", "plan_chunks", "init_slots", "insert_request",
           "PagePool", "RadixPrefixTree", "init_paged_slots",
           "export_slot", "import_slot", "HostKVTier",
           "Autoscaler", "AutoscaleConfig", "AutoscaleDecision",
           "RequestStatus", "QueueFullError", "PagePoolExhausted"]
