"""Paged KV cache: page-pool allocator, radix prefix tree, device programs.

Reference analog: vLLM's PagedAttention block manager and the
DeepSpeed-MII/FastGen blocked KV cache, rebuilt static-shape-native. The
device state is ONE ``(L, pages, KV, page_size, hd)`` pool (per K and V,
via the shared :func:`~..inference.decode.cache_layout`) plus integer
per-slot page tables in the decode carry; the attention read gathers
over page ids, so page indirection is DATA — traffic churn changes table
contents, never a compiled program.

The host half lives here too:

- :class:`PagePool` — free-list allocator with per-page refcounts split
  into slot references (live requests) and tree references (the prefix
  cache's own retention). A page frees when both hit zero; tree-held
  pages with no slot users are the eviction pool under pressure (LRU).
- :class:`RadixPrefixTree` — one node per ``page_size``-token block of
  registered prompts. An admitted prompt walks the tree: every matched
  block is a pool page the request SHARES (refcount++, no prefill, no
  copy); the first divergent, partially-matched tail block is the one
  copy-on-write site — its source page is gathered into the request's
  prefill cache (``hydrate``) and written back to a FRESH private page
  at insert, so the donor's page is never mutated.
- tiered host store (``serving/hostkv.py``, ``host`` / ``on_demote``
  seams) — eviction demotes full-block tree entries to pinned host
  memory instead of dropping them, and admission consults the tier
  right after the radix-tree match: matched cold blocks restore at copy
  bandwidth (their tokens join ``skip``) instead of recompute FLOPs.
  ``prefill_tokens_saved`` counts restored tokens too — it is the
  "tokens not recomputed" truth; the tier's own counters split out
  what was paid in copy bytes.
- admission math — a request's worst-case page need assumes zero
  sharing (shared pages can be evicted from under the queue), so a
  request the pool can NEVER hold sheds with a typed
  :class:`~..resilience.guards.PagePoolExhausted` at submit, and a
  transiently full pool defers the queue head until retirement frees
  pages: the OOM-shaped mid-decode crash is impossible by construction.

Pool page 0 is reserved scratch: idle slots' table rows point there, and
the insert scatter redirects shared-page entries there — a retired slot
or a shared prefix can never be written by construction.

Metrics land in the serving registry (``Serve/page_*``); ``snapshot()``
is the flight-recorder provider, so a stall dump shows pool state.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..inference.decode import GenCarry, PagedKVCache, cache_layout, \
    dequantize_kv, quantize_kv
from ..resilience.guards import PagePoolExhausted

__all__ = ["PagePool", "RadixPrefixTree", "PageAllocation",
           "init_paged_slots", "insert_paged", "hydrate_cache",
           "export_slot", "import_slot", "PagePoolExhausted"]

_SCRATCH = 0        # reserved pool page: idle-slot / shared-entry sink


# ------------------------------------------------------------ device side
def init_paged_slots(cfg, slots: int, max_len: int, page_size: int,
                     pages: int, dtype=None, kv_quant_bits: int = 0) \
        -> GenCarry:
    """Empty paged slot state: all slots idle (``done``), tables on
    scratch, length 0. The carry is a plain GenCarry whose cache is a
    :class:`~..inference.decode.PagedKVCache`, so the SAME ``decode_step``
    serves the contiguous and paged worlds."""
    shape, dt = cache_layout(cfg, slots, max_len, dtype,
                             page_size=page_size, pages=pages)
    if kv_quant_bits == 8:
        pool_dt, ks = jnp.int8, jnp.ones(shape[:-1], jnp.float32)
        k_scale, v_scale = ks, ks
    else:
        pool_dt, k_scale, v_scale = dt, None, None
    n = max_len // page_size
    cache = PagedKVCache(
        k=jnp.zeros(shape, pool_dt), v=jnp.zeros(shape, pool_dt),
        k_scale=k_scale, v_scale=v_scale,
        page_table=jnp.zeros((slots, n), jnp.int32),
        length=jnp.zeros((slots,), jnp.int32))
    return GenCarry(tok=jnp.zeros((slots,), jnp.int32), cache=cache,
                    rng=jnp.zeros((slots, 2), jnp.uint32),
                    done=jnp.ones((slots,), bool))


def _page_split(buf, n: int, ps: int):
    """A batch-1 contiguous cache buffer (L, 1, KV, n*ps, hd) viewed as
    per-page tiles (L, n, KV, ps, hd) — the relayout-free bridge between
    the prefill lane and the pool (both orderings are position-major)."""
    L, _, KV, _, hd = buf.shape
    return buf[:, 0].reshape(L, KV, n, ps, hd).transpose(0, 2, 1, 3, 4)


def _page_merge(tiles, like):
    """Inverse of :func:`_page_split`: per-page tiles back into the
    batch-1 contiguous layout of ``like``."""
    L, _, KV, max_len, hd = like.shape
    return tiles.transpose(0, 2, 1, 3, 4).reshape(
        L, 1, KV, max_len, hd)


def insert_paged(state: GenCarry, slot, pf: GenCarry, page_row,
                 first_private) -> GenCarry:
    """Scatter a freshly prefilled request's contiguous cache into its
    pool pages and seat the per-slot vectors.

    ``page_row`` is the slot's full (pages_per_slot,) table row;
    ``first_private`` the count of leading SHARED pages — those scatter
    targets are redirected to the scratch page, so a shared prefix is
    never rewritten (the prefill cache holds bit-identical hydrated
    values there anyway; redirecting keeps the write traffic off the
    live pages). Every PRIVATE page of the row is overwritten across its
    full extent — the paged analog of ``insert_request``'s
    stale-KV-leak-impossible-by-construction contract. Quantized pools
    quantize here, on append, with the same per-token per-head scales
    the decode-step append uses."""
    c = state.cache
    n, ps = page_row.shape[0], c.k.shape[3]
    tgt = jnp.where(jnp.arange(n) >= first_private, page_row, _SCRATCH)
    vk, vv = _page_split(pf.cache.k, n, ps), _page_split(pf.cache.v, n, ps)
    if c.k_scale is not None:
        qk, sk = quantize_kv(vk)
        qv, sv = quantize_kv(vv)
        k = c.k.at[:, tgt].set(qk)
        v = c.v.at[:, tgt].set(qv)
        k_scale = c.k_scale.at[:, tgt].set(sk)
        v_scale = c.v_scale.at[:, tgt].set(sv)
    else:
        k = c.k.at[:, tgt].set(vk.astype(c.k.dtype))
        v = c.v.at[:, tgt].set(vv.astype(c.v.dtype))
        k_scale, v_scale = c.k_scale, c.v_scale
    length = lax.dynamic_update_slice(
        c.length, pf.cache.length.reshape(1).astype(jnp.int32), (slot,))
    tok = lax.dynamic_update_slice(state.tok, pf.tok.astype(jnp.int32),
                                   (slot,))
    rng = lax.dynamic_update_slice(state.rng, pf.rng, (slot, 0))
    done = lax.dynamic_update_slice(state.done, pf.done, (slot,))
    cache = PagedKVCache(k=k, v=v, k_scale=k_scale, v_scale=v_scale,
                         page_table=c.page_table, length=length)
    return GenCarry(tok=tok, cache=cache, rng=rng, done=done)


def hydrate_cache(state: GenCarry, cache, hydrate_row, count):
    """Fill the leading pages of a batch-1 prefill cache from the pool:
    the admission-time half of prefix sharing. ``hydrate_row`` is a full
    (pages_per_slot,) id vector (entries past ``count`` ignored), so ONE
    compiled program serves every shared-prefix length. The last entry
    may be a copy-on-write SOURCE page (a donor's partially-matched tail
    block): its bytes bounce through this cache and land in a fresh
    private page at insert — the donor page itself is never written.
    Int8 pools dequantize here; the suffix prefill then runs in the
    compute dtype exactly as an unshared request's would."""
    c = state.cache
    n = hydrate_row.shape[0]
    gk, gv = c.k[:, hydrate_row], c.v[:, hydrate_row]  # (L, n, KV, ps, hd)
    if c.k_scale is not None:
        gk = dequantize_kv(gk, c.k_scale[:, hydrate_row], cache.k.dtype)
        gv = dequantize_kv(gv, c.v_scale[:, hydrate_row], cache.v.dtype)
    else:
        gk = gk.astype(cache.k.dtype)
        gv = gv.astype(cache.v.dtype)
    ps = c.k.shape[3]
    keep = (jnp.arange(n) < count)[None, :, None, None, None]
    ck = jnp.where(keep, gk, _page_split(cache.k, n, ps))
    cv = jnp.where(keep, gv, _page_split(cache.v, n, ps))
    return cache._replace(k=_page_merge(ck, cache.k),
                          v=_page_merge(cv, cache.v))


def export_slot(state: GenCarry, row, slot) -> dict:
    """Gather ONE request's pool pages + per-slot decode vectors into a
    position-major payload: the SOURCE half of the disaggregated
    prefill→decode handoff (serving/fleet.py). ``row`` is the slot's
    full (pages_per_slot,) table row — page indirection is DATA, so one
    compiled program exports any request on any slot. The payload is the
    request's complete decode state: its prompt KV tiles (int8 pools
    include the scale planes), the first sampled token, the per-request
    RNG chain *after* that sample, the done flag, and the cache length —
    everything a decode replica needs to continue the exact bit-stream.
    The caller ``device_get``s the result: the transfer is host-mediated
    by design (replicas share no device state)."""
    c = state.cache
    out = {"k": c.k[:, row], "v": c.v[:, row],           # (L, n, KV, ps, hd)
           "tok": lax.dynamic_slice(state.tok, (slot,), (1,)),
           "rng": lax.dynamic_slice(state.rng, (slot, 0), (1, 2)),
           "done": lax.dynamic_slice(state.done, (slot,), (1,)),
           "length": lax.dynamic_slice(c.length, (slot,), (1,))}
    if c.k_scale is not None:
        out["k_scale"] = c.k_scale[:, row]
        out["v_scale"] = c.v_scale[:, row]
    return out


def import_slot(state: GenCarry, slot, payload: dict, row,
                first_private) -> GenCarry:
    """Scatter an exported payload into THIS pool's pages and seat the
    slot vectors: the DESTINATION half of the handoff. ``row`` is the
    destination allocation's table row; tiles below ``first_private``
    (prefix pages the destination already shares via its own radix tree
    — bit-identical KV by the parity oracle) redirect to the scratch
    page exactly like :func:`insert_paged`'s shared entries, so a live
    shared page is never rewritten. Every private page is overwritten
    across its full extent (the stale-KV-impossible contract); garbage
    tiles beyond ``length`` are invisible to the per-row attention mask
    and progressively overwritten by decode appends."""
    c = state.cache
    n = row.shape[0]
    tgt = jnp.where(jnp.arange(n) >= first_private, row, _SCRATCH)
    k = c.k.at[:, tgt].set(payload["k"].astype(c.k.dtype))
    v = c.v.at[:, tgt].set(payload["v"].astype(c.v.dtype))
    if c.k_scale is not None:
        k_scale = c.k_scale.at[:, tgt].set(payload["k_scale"])
        v_scale = c.v_scale.at[:, tgt].set(payload["v_scale"])
    else:
        k_scale, v_scale = c.k_scale, c.v_scale
    length = lax.dynamic_update_slice(
        c.length, payload["length"].astype(jnp.int32), (slot,))
    tok = lax.dynamic_update_slice(state.tok,
                                   payload["tok"].astype(jnp.int32), (slot,))
    rng = lax.dynamic_update_slice(state.rng, payload["rng"], (slot, 0))
    done = lax.dynamic_update_slice(state.done, payload["done"], (slot,))
    cache = PagedKVCache(k=k, v=v, k_scale=k_scale, v_scale=v_scale,
                         page_table=c.page_table, length=length)
    return GenCarry(tok=tok, cache=cache, rng=rng, done=done)


# -------------------------------------------------------------- host side
@dataclasses.dataclass
class PageAllocation:
    """One admitted request's page plan, produced by
    :meth:`PagePool.try_admit` and carried on the ``Request``.

    ``row`` is the full table row (shared ids, then private ids, then
    scratch padding); ``shared`` the leading shared-page count (=
    ``first_private`` for the insert scatter); ``skip`` the prompt
    tokens the prefill lane does NOT recompute (hydrated instead);
    ``hydrate_row``/``hydrate_pages`` the gather plan (``hydrate_pages``
    may exceed ``shared`` by one: the copy-on-write source page)."""

    rid: int
    row: np.ndarray
    pages: int                  # live pages this request references
    shared: int                 # leading pages shared via the prefix tree
    skip: int                   # prompt tokens served from the pool
    hydrate_row: np.ndarray
    hydrate_pages: int
    cow: bool = False           # a partially-matched tail page was copied
    cow_src: Optional[int] = None   # donor page pinned until insert/abort
    registered: bool = False
    # host-tier restore plan (serving/hostkv.py): ``restored`` cold
    # blocks continue the tree match from pinned host memory — their
    # tiles ride here to the engine's restore scatter, their tokens are
    # counted into ``skip`` (restored, not recomputed), and their pages
    # are ordinary private pages that ``insert_paged`` overwrites and
    # ``on_inserted`` registers into the tree like any other prefill.
    restored: int = 0
    restore_tiles: Optional[dict] = None
    restore_tokens: int = 0
    restore_bytes: int = 0


class _Node:
    """One radix-tree node = one ``page_size``-token block of some
    registered prompt, holding the pool page with that block's KV.
    ``tails`` maps partially-filled trailing blocks (prompt length not
    page-aligned) to their pages — the copy-on-write sources."""

    __slots__ = ("children", "tails", "page", "stamp", "tstamp", "parent",
                 "key")

    def __init__(self, parent=None, key=None, page: int = -1):
        self.children: dict = {}
        self.tails: dict = {}          # tail tokens (tuple) -> page id
        self.page = page
        self.stamp = 0
        self.tstamp: "float | None" = None   # clock time of the last touch
        self.parent = parent
        self.key = key


class RadixPrefixTree:
    """Host-side prefix index over registered prompt blocks.

    ``match`` walks an admitted prompt block-by-block, returning the
    shared page run and (optionally) a copy-on-write tail source;
    ``register`` adds a freshly inserted request's prompt blocks under
    its own private pages. Eviction is leaf-first LRU and only ever
    offered pages with zero slot references — the pool drives it when
    allocation runs dry."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.root = _Node()
        self._tick = 0
        # eviction-pressure clock: the POOL stamps this before walking
        # the tree (one clock read per match/register call, only when
        # the pool was given a clock — the kvscope opt-in); None keeps
        # entry ages unreported and the hot path clock-free.
        self.now: "float | None" = None

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.stamp = self._tick
        if self.now is not None:
            node.tstamp = self.now

    def match(self, prompt: np.ndarray) -> tuple:
        """(shared page ids, cow (src_page, tail_len) | None)."""
        toks = np.asarray(prompt).reshape(-1)
        ps = self.page_size
        node, ids = self.root, []
        i = 0
        while i + ps <= len(toks):
            child = node.children.get(tuple(toks[i:i + ps].tolist()))
            if child is None:
                break
            ids.append(child.page)
            self._touch(child)
            node, i = child, i + ps
        cow = None
        rest = tuple(toks[i:].tolist())
        for tail, page in node.tails.items():
            if len(tail) <= len(rest) and rest[:len(tail)] == tail \
                    and (cow is None or len(tail) > cow[1]):
                cow = (page, len(tail))
        return ids, cow

    def peek_blocks(self, toks: np.ndarray) -> int:
        """Leading full blocks of ``toks`` the tree holds, WITHOUT
        touching LRU stamps — :meth:`match`'s walk minus its side
        effects, for read-only probes (the fleet router's residency
        ranking must not distort eviction order on replicas it only
        considered)."""
        ps = self.page_size
        node, i, n = self.root, 0, 0
        while i + ps <= len(toks):
            child = node.children.get(tuple(toks[i:i + ps].tolist()))
            if child is None:
                break
            n += 1
            node, i = child, i + ps
        return n

    def register(self, prompt: np.ndarray, row: np.ndarray) -> list:
        """Index a just-inserted request's prompt blocks: full blocks as
        child nodes, a trailing partial block as a tail entry. Blocks
        already present keep their existing page (first writer wins — the
        duplicate private copy stays private). Returns the page ids the
        TREE newly references (the pool adds tree refs for them)."""
        toks = np.asarray(prompt).reshape(-1)
        ps = self.page_size
        node, taken = self.root, []
        for b in range(len(toks) // ps):
            key = tuple(toks[b * ps:(b + 1) * ps].tolist())
            child = node.children.get(key)
            if child is None:
                child = node.children[key] = _Node(
                    parent=node, key=key, page=int(row[b]))
                taken.append(child.page)
            self._touch(child)
            node = child
        tail = tuple(toks[(len(toks) // ps) * ps:].tolist())
        if tail and tail not in node.tails:
            node.tails[tail] = int(row[len(toks) // ps])
            taken.append(node.tails[tail])
        return taken

    def evictable(self) -> list:
        """(stamp, kind, node, key, page) for every leaf-evictable entry:
        tail entries, and childless tail-less nodes — oldest first."""
        out = []

        def walk(node):
            for tail, page in node.tails.items():
                out.append((node.stamp, "tail", node, tail, page))
            for key, child in node.children.items():
                if not child.children and not child.tails:
                    out.append((child.stamp, "node", node, key, child.page))
                else:
                    walk(child)

        walk(self.root)
        out.sort(key=lambda e: e[0])
        return out

    def drop(self, kind: str, parent: _Node, key) -> None:
        if kind == "tail":
            parent.tails.pop(key, None)
        else:
            parent.children.pop(key, None)

    @staticmethod
    def entry_tokens(node: _Node, key) -> tuple:
        """The FULL token prefix an evictable entry caches, from the
        root through ``key`` (a child-block tuple or a tail tuple under
        ``node``) — the identity the ghost-tree regret ledger
        (``observability/kvscope.py``) stamps at eviction so a later
        admission of the same prefix is attributable to the eviction
        that made it expensive."""
        parts = []
        while node is not None and node.key is not None:
            parts.append(node.key)
            node = node.parent
        parts.reverse()
        return tuple(t for k in parts for t in k) + tuple(key)

    def oldest_entry_time(self) -> "float | None":
        """Touch time of the oldest evictable entry (None without a
        clock or an evictable entry) — ``now - this`` is the
        eviction-pressure age ``PagePool.snapshot()`` surfaces. One
        sort-free walk (snapshot runs on every health/readyz probe;
        ``evictable()``'s sorted list would pay O(n log n) per probe)."""
        best = None

        def consider(n):
            nonlocal best
            if n.tstamp is not None and (best is None
                                         or n.tstamp < best):
                best = n.tstamp

        stack = [self.root]
        while stack:
            node = stack.pop()
            if node.tails:
                consider(node)
            for child in node.children.values():
                if not child.children and not child.tails:
                    consider(child)
                else:
                    stack.append(child)
        return best

    def __len__(self) -> int:
        n = 0
        stack = [self.root]
        while stack:
            node = stack.pop()
            n += len(node.tails)
            for child in node.children.values():
                n += 1
                stack.append(child)
        return n


class PagePool:
    """Host allocator over the device pool's page ids (1..pages-1; page 0
    is scratch). Tracks, per page, slot references (live requests whose
    table rows include it) and ONE optional tree reference (the prefix
    cache retains it for future sharing); a page returns to the free
    list when both drop. All decisions are host-side numpy/dicts — zero
    device syncs, zero compiled programs."""

    def __init__(self, pages: int, page_size: int, max_len: int,
                 registry=None, prefix_sharing: bool = True, clock=None):
        if pages < 2:
            raise ValueError(f"page pool needs >= 2 pages (one is "
                             f"reserved scratch), got {pages}")
        self.pages = pages
        self.page_size = page_size
        self.pages_per_slot = max_len // page_size
        self.registry = registry
        # injectable clock for eviction-pressure ages (oldest tree-entry
        # age in snapshot()/health()); None (default) keeps the pool
        # entirely clock-free — the engine passes one only when the
        # kvscope residency observatory opted in
        self.clock = clock
        self.free: list[int] = list(range(pages - 1, 0, -1))  # pop() -> 1..
        self.slot_refs = np.zeros(pages, np.int64)
        self.tree_refs = np.zeros(pages, bool)
        self.tree: Optional[RadixPrefixTree] = \
            RadixPrefixTree(page_size) if prefix_sharing else None
        self._alloc: dict[int, PageAllocation] = {}   # rid -> allocation
        # bumped whenever admission prospects improve (pages freed by a
        # release, or new prefixes registered): the scheduler's retry
        # gate, so a deferred queue head re-runs the tree match/eviction
        # walk only when something actually changed
        self.generation = 0
        # eviction-stamp seam (observability/kvscope.py): called once
        # per eviction EVENT with the evicted entries' token prefixes —
        # the ghost-tree regret ledger's input. None (default) = one
        # `is not None` per eviction pass, nothing else.
        self.on_evict = None
        # tiered-KV seams (serving/hostkv.py): ``host`` is the engine's
        # HostKVTier — admission consults it right after the radix-tree
        # match and restores matched cold blocks instead of recomputing
        # them; ``on_demote`` is called during an eviction pass with the
        # evicted FULL-BLOCK entries (page id + token prefix) BEFORE
        # their pages can be reused, so the engine can gather the tiles
        # to host. Both None (default) = one `is not None` per
        # admission/eviction, nothing else.
        self.host = None
        self.on_demote = None
        # page-residency seam (observability/tenantscope.py): called as
        # ``on_pages(rid, delta)`` with the SAME page counts the pool
        # books — +pages at admission, -pages at truncate rollback,
        # -pages at release — so a per-tenant page-second integral sums
        # to the pool's own occupancy exactly. None (default) = one
        # `is not None` per admission/release, nothing else.
        self.on_pages = None
        # cumulative accounting (the capacity advisor's "achieved" side).
        # `evictions` counts PAGES freed by tree eviction (the historical
        # meaning, kept); `eviction_events` counts eviction PASSES — one
        # admission under pressure is one event however many pages it
        # reclaims. The two answer different questions (how much cache
        # was lost vs how often pressure bites) and are reported apart.
        self.prefill_tokens_saved = 0
        self.prompt_tokens = 0
        self.shared_page_acquires = 0
        self.private_page_acquires = 0
        self.cow_copies = 0
        self.evictions = 0              # pages freed by eviction
        self.eviction_events = 0        # eviction passes that freed > 0
        self.defers = 0
        self._publish()

    # ------------------------------------------------------------- metrics
    def _publish(self) -> None:
        if self.registry is None:
            return
        self.registry.set_gauges({
            "Serve/page_pool_free": float(len(self.free)),
            "Serve/page_pool_used": float(self.usable - len(self.free)),
            "Serve/page_pool_tree_held": float(self.tree_held),
            "Serve/page_prefix_hit_rate": self.prefix_hit_rate,
        })

    @property
    def usable(self) -> int:
        return self.pages - 1

    @property
    def tree_held(self) -> int:
        """Pages retained ONLY by the prefix tree (evictable)."""
        return int(np.sum(self.tree_refs & (self.slot_refs == 0)))

    @property
    def prefix_hit_rate(self) -> float:
        total = self.shared_page_acquires + self.private_page_acquires
        return self.shared_page_acquires / total if total else 0.0

    def worst_case_pages(self, prompt_len: int, max_new: int) -> int:
        """Pages a request can need assuming ZERO sharing — the admission
        bound (shared pages are real at admission time, but the bound
        must hold even when the tree has nothing to offer)."""
        return -(-(prompt_len + max_new - 1) // self.page_size)

    def check_submit(self, prompt_len: int, max_new: int) -> None:
        """Typed shed for a request the pool can NEVER hold — raising at
        submit() keeps the failure synchronous instead of wedging the
        queue head forever."""
        need = self.worst_case_pages(prompt_len, max_new)
        if need > self.usable:
            raise PagePoolExhausted(
                f"request needs up to {need} KV pages (prompt {prompt_len}"
                f" + max_new {max_new} @ page_size {self.page_size}) but "
                f"the pool holds {self.usable} — raise serving.pool_pages "
                "or shrink the request", pages_needed=need,
                pages_usable=self.usable)

    # ----------------------------------------------------------- admission
    def _evict(self, need: int) -> bool:
        """Free ``need`` pages by dropping LRU tree entries with no slot
        users. Returns False (nothing dropped beyond what was possible)
        when the tree cannot cover the shortfall."""
        if self.tree is None or need <= 0:
            return need <= 0
        freed = 0
        ghosts = [] if self.on_evict is not None else None
        demote = [] if self.on_demote is not None else None
        while freed < need:
            # leaf-first passes: dropping a leaf can expose its parent as
            # the next evictable entry, so re-snapshot until the need is
            # met or a pass frees nothing (everything left is pinned)
            progress = False
            for _stamp, kind, parent, key, page in self.tree.evictable():
                if freed >= need:
                    break
                if self.slot_refs[page] == 0 and self.tree_refs[page]:
                    if ghosts is not None:
                        # stamp the evicted block's identity BEFORE the
                        # drop: the ghost ledger attributes the prefill
                        # a later admission re-pays to THIS event
                        ghosts.append({
                            "tokens": self.tree.entry_tokens(parent, key),
                            "block": len(key)})
                    if demote is not None and kind == "node":
                        # demote-on-evict: full blocks carry a complete
                        # page of KV worth keeping; partial tails stay
                        # ghost-only (copy-on-write sources are cheap
                        # to recompute and block-granular keys keep the
                        # tier's restore walk trivial)
                        demote.append({
                            "tokens": self.tree.entry_tokens(parent, key),
                            "page": int(page), "block": len(key)})
                    self.tree.drop(kind, parent, key)
                    self.tree_refs[page] = False
                    self.free.append(page)
                    self.evictions += 1
                    freed += 1
                    progress = True
            if not progress:
                break
        if freed:
            self.eviction_events += 1
            if self.registry is not None:
                # pages freed and eviction EVENTS are different signals:
                # Serve/page_evictions keeps its historical pages-freed
                # meaning, the event counter says how often pressure bit
                self.registry.counter("Serve/page_evictions").inc(freed)
                self.registry.counter("Serve/page_eviction_events").inc()
            if demote:
                # BEFORE the freed pages can be popped for reuse: the
                # engine's handler DISPATCHES the tile gather here (so
                # it is ordered ahead of any program that could rewrite
                # the pages) and materializes it to host at the end of
                # the iteration, off the admission path
                self.on_demote(demote)
            if ghosts:
                self.on_evict(ghosts)
        return freed >= need

    def demote_ahead_candidates(self, cutoff: float, limit: int,
                                skip=None) -> list:
        """Full-block tree entries whose last touch is at or before
        ``cutoff`` and whose page has no slot users — the demote-ahead
        lane's feed, shaped exactly like the ``on_demote`` payload
        (``tokens`` / ``page`` / ``block``). Unlike eviction's
        leaf-first passes, this walks EVERY block node of an idle chain
        (an idle session's whole prefix stages in one batch, not one
        block per pass — inner nodes are full blocks too; shared-prefix
        nodes another session still touches stay above the cutoff).
        Read-only: no drops, no stamp touches, no refcount changes —
        the pages stay tree-held and a resuming session keeps them as a
        normal tree hit (staging is a COPY, so a resume mid-stage
        wastes at most that one copy; tree-held pages with no slot
        users are immutable, so the copy can never go stale). Partial
        tails stay recompute-only, same as eviction's demote filter.
        ``skip(tokens)`` filters entries already staged (the tier's
        ``holds``); oldest first, at most ``limit``. Requires the pool
        clock (entries without a ``tstamp`` never qualify)."""
        if self.tree is None or limit <= 0:
            return []
        cands: list = []

        def walk(node):
            for key, child in node.children.items():
                if (child.tstamp is not None and child.tstamp <= cutoff
                        and self.slot_refs[child.page] == 0
                        and self.tree_refs[child.page]):
                    cands.append((child.tstamp, node, key, child.page))
                walk(child)

        walk(self.tree.root)
        cands.sort(key=lambda c: c[0])
        out = []
        for _ts, parent, key, page in cands:
            if len(out) >= limit:
                break
            toks = self.tree.entry_tokens(parent, key)
            if skip is not None and skip(toks):
                continue
            out.append({"tokens": toks, "page": int(page),
                        "block": len(key)})
        return out

    def try_admit(self, prompt: np.ndarray, max_new: int,
                  rid: int, book_savings: bool = True) \
            -> Optional[PageAllocation]:
        """Admission-time page plan: consult the prefix tree, take refs
        on the shared run, allocate private pages for the rest (evicting
        LRU tree-only pages under pressure). None = transiently full —
        the caller leaves the request at the queue head and retries
        after a retirement.

        ``book_savings=False`` (the disaggregated IMPORT path) still
        allocates and shares pages but books no prefill-savings or
        copy-on-write stats: a decode replica seating already-computed
        KV skips no prefill compute, so counting its ``skip`` tokens as
        saved would double-count the prefill replica's real savings."""
        prompt = np.asarray(prompt).reshape(-1)
        P, ps, n = len(prompt), self.page_size, self.pages_per_slot
        if self.tree is not None and self.clock is not None:
            # one clock read per admission: every node the walk touches
            # gets this stamp, so entry AGES (eviction pressure) are
            # reportable without a read per node
            self.tree.now = self.clock()
        shared_ids, cow = (self.tree.match(prompt)
                           if self.tree is not None else ([], None))
        total_need = self.worst_case_pages(P, max_new)
        if total_need > n:
            # unreachable through the scheduler (P + max_new <= max_len);
            # a direct caller exceeding the slot extent is a bug, not
            # backpressure
            raise ValueError(
                f"request needs {total_need} pages > pages_per_slot={n} "
                "(prompt + max_new exceeds max_len)")
        # a fully-shared prompt still recomputes its final token (the
        # first output's logits need a forward at position P-1), so cap
        # the skip below P; the replayed bucket rewrites bit-identical KV
        shared = min(len(shared_ids), total_need)
        shared_ids = shared_ids[:shared]
        skip = shared * ps
        # host-tier restore plan (serving/hostkv.py): cold full blocks
        # CONTINUING the tree match, pinned in the tier until this
        # allocation commits (consume) or defers (release). Their pages
        # are ordinary private pages; only ``skip`` and the tile payload
        # distinguish a restore from a recompute. The disaggregated
        # import path (book_savings=False) seats already-computed KV and
        # must not burn host copies it would never read.
        restore_keys: list = []
        if self.host is not None and book_savings:
            restore_keys = self.host.match(
                prompt, start_block=shared,
                max_blocks=total_need - shared)
        restored = len(restore_keys)
        skip += restored * ps
        # a restored full block covers any copy-on-write tail at the
        # same position — cow only applies to an unrestored admission
        cow_src, cow_len = (cow if restored == 0 and cow is not None
                            and cow[1] > 0 and skip + cow[1] < P
                            else (None, 0))
        private_need = total_need - shared
        # pin the matched pages BEFORE any eviction pass: a tree-only
        # page we are about to share must not be reclaimed to cover the
        # same request's private shortfall
        for p in shared_ids:
            self.slot_refs[p] += 1
        if cow_src is not None:
            self.slot_refs[cow_src] += 1
        short = private_need - len(self.free)
        if short > 0 and not self._evict(short):
            for p in shared_ids:           # undo the pins; defer in queue
                self._unref(p)
            if cow_src is not None:
                self._unref(cow_src)
            if restore_keys:
                # the cold blocks stay restorable for the retry
                self.host.release(restore_keys)
            self.defers += 1
            if self.registry is not None:
                self.registry.counter("Serve/page_defers").inc()
            return None
        private = [self.free.pop() for _ in range(private_need)]
        row = np.zeros(n, np.int32)
        row[:shared] = shared_ids
        row[shared:total_need] = private
        for p in private:
            self.slot_refs[p] += 1
        hyd = np.zeros(n, np.int32)
        hyd[:shared] = shared_ids
        hydrate_pages = shared
        if cow_src is not None:
            # copy-on-write: the donor's partial tail block bounces
            # through the prefill cache into this request's own page
            # (the pin above holds until insert/abort)
            hyd[shared] = cow_src
            hydrate_pages = shared + 1
            skip += cow_len
            if book_savings:
                self.cow_copies += 1
                if self.registry is not None:
                    self.registry.counter("Serve/page_cow_copies").inc()
        skip = min(skip, P - 1)
        tiles, rbytes, rtoks = None, 0, 0
        if restore_keys:
            # commit point: the pinned host copies move onto the
            # allocation (the engine scatters them into the prefill
            # cache before the suffix chunks run)
            tiles, rbytes, rtoks = self.host.consume(restore_keys)
        alloc = PageAllocation(
            rid=rid, row=row, pages=total_need, shared=shared, skip=skip,
            hydrate_row=hyd, hydrate_pages=hydrate_pages,
            cow=cow_src is not None, cow_src=cow_src,
            restored=restored, restore_tiles=tiles,
            restore_tokens=rtoks, restore_bytes=rbytes)
        self._alloc[rid] = alloc
        if book_savings:
            self.prompt_tokens += P
            self.prefill_tokens_saved += skip
            if self.registry is not None:
                self.registry.counter(
                    "Serve/page_prefill_tokens_saved").inc(skip)
        self.shared_page_acquires += shared
        self.private_page_acquires += private_need
        if self.registry is not None:
            self.registry.histogram(
                "Serve/pages_per_request").observe(total_need)
        self._publish()
        if self.on_pages is not None:
            self.on_pages(rid, total_need)
        return alloc

    # ---------------------------------------------------------- completion
    def on_inserted(self, rid: int, prompt: np.ndarray) -> None:
        """The request's prefill landed in the pool: register its prompt
        blocks in the prefix tree (tree refs on its own private pages)
        and release the copy-on-write source pin."""
        alloc = self._alloc.get(rid)
        if alloc is None or alloc.registered:
            return
        alloc.registered = True
        self._release_cow(alloc)
        if self.tree is not None:
            if self.clock is not None:
                self.tree.now = self.clock()
            for page in self.tree.register(np.asarray(prompt), alloc.row):
                self.tree_refs[page] = True
        self.generation += 1
        self._publish()

    def _release_cow(self, alloc: PageAllocation) -> None:
        if alloc.cow_src is not None:
            src, alloc.cow_src = alloc.cow_src, None
            self._unref(src)

    def _unref(self, page: int) -> None:
        self.slot_refs[page] -= 1
        if self.slot_refs[page] <= 0:
            self.slot_refs[page] = 0
            if not self.tree_refs[page]:
                self.free.append(page)

    def release(self, rid: int) -> None:
        """Terminal path (retire / cancel / timeout / nonfinite / shed
        after allocation): drop the request's slot refs; pages with no
        tree reference return to the free list immediately."""
        alloc = self._alloc.pop(rid, None)
        if alloc is None:
            return
        self._release_cow(alloc)
        for page in alloc.row[:alloc.pages]:
            self._unref(int(page))
        self.generation += 1
        self._publish()
        if self.on_pages is not None:
            # alloc.pages already reflects any truncate rewinds, so the
            # admission/truncate/release deltas net to zero per rid
            self.on_pages(rid, -alloc.pages)

    def truncate(self, rid: int, new_tokens: int) -> int:
        """Page-table-aware rollback: rewind ``rid``'s live extent to
        ``new_tokens`` tokens, freeing the whole pages strictly beyond
        the (kept, partially-filled) tail block. The speculative-decode
        lane calls this when a request retires off a verify step whose
        rejected drafts wrote past the final committed length — the
        garbage tail's pages drop their slot refs immediately instead of
        riding to ``release``, and can never be mistaken for live KV by
        a later demotion sweep.

        Invariants preserved:

        - never rewinds below ``alloc.shared`` (tree-pinned prefix pages
          and host-restored blocks are admission-time state, not
          decode-time growth — rollback cannot unshare them);
        - freed entries go through :meth:`_unref`, so a page the prefix
          tree still references stays resident for future hits (host-tier
          demotion candidates included) and only truly unreferenced
          pages hit the free list;
        - the table row's freed entries redirect to scratch, so a stale
          device mirror of this row can only ever write into page 0;
        - ``generation`` bumps like every other occupancy change, so
          deferred admissions retry against the freed pages.

        Returns the number of pages freed. Partial-tail rewinds within
        one block free nothing — the tail block is KEPT and its
        positions past ``new_tokens`` are dead by length (every future
        append overwrites position == committed length first)."""
        alloc = self._alloc.get(rid)
        if alloc is None:
            return 0
        keep = -(-max(0, int(new_tokens)) // self.page_size)
        keep = min(alloc.pages, max(keep, alloc.shared))
        freed = alloc.pages - keep
        if freed <= 0:
            return 0
        for page in alloc.row[keep:alloc.pages]:
            self._unref(int(page))
        alloc.row[keep:alloc.pages] = _SCRATCH
        alloc.pages = keep
        self.generation += 1
        self._publish()
        if self.on_pages is not None:
            self.on_pages(rid, -freed)
        return freed

    # -------------------------------------------------------------- readout
    def residency(self, prompt: np.ndarray) -> tuple:
        """``(tree_blocks, host_blocks)`` holding ``prompt``'s leading
        full blocks right now — a READ-ONLY probe for the fleet
        router's affinity ranking (tree hit > host-tier hit > miss):
        no LRU touches, no refcounts, no pins, so routing a session
        cannot distort eviction order on replicas it only considered."""
        if self.tree is None:
            return (0, 0)
        toks = np.asarray(prompt).reshape(-1)
        tree_blocks = self.tree.peek_blocks(toks)
        host_blocks = (self.host.peek_blocks(toks, tree_blocks)
                       if self.host is not None else 0)
        return tree_blocks, host_blocks

    def snapshot(self) -> dict:
        """Flight-recorder provider + the capacity advisor's achieved
        side: pool occupancy, sharing effectiveness, tree size, and the
        eviction-pressure picture (evictable pages, oldest tree-entry
        age — surfaced through health()/ /readyz)."""
        used = self.usable - len(self.free)
        oldest_age = None
        if self.tree is not None and self.clock is not None:
            t = self.tree.oldest_entry_time()
            if t is not None:
                oldest_age = max(0.0, self.clock() - t)
        return {
            "pages": self.pages,
            "usable_pages": self.usable,
            "page_size": self.page_size,
            "pages_per_slot": self.pages_per_slot,
            "free_pages": len(self.free),
            "used_pages": used,
            "tree_held_pages": self.tree_held,
            "tree_entries": len(self.tree) if self.tree is not None else 0,
            # tree-held pages are reclaimable cache, not waste; the
            # fragmentation figure is the share of the pool neither a
            # slot nor the tree can account for (0 by construction —
            # page granularity leaves nothing stranded)
            "fragmentation": max(0, used - self.tree_held - int(
                np.sum(self.slot_refs > 0))) / max(1, self.usable),
            "live_requests": len(self._alloc),
            "prompt_tokens": self.prompt_tokens,
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "tokens_saved_fraction": (
                self.prefill_tokens_saved / self.prompt_tokens
                if self.prompt_tokens else 0.0),
            "shared_page_acquires": self.shared_page_acquires,
            "private_page_acquires": self.private_page_acquires,
            "prefix_hit_rate": self.prefix_hit_rate,
            "cow_copies": self.cow_copies,
            "evictions": self.evictions,
            # eviction pressure, disaggregated: how much cache was lost
            # (pages) vs how often pressure bit (events), what could be
            # reclaimed right now, and how stale the coldest entry is
            "pages_evicted": self.evictions,
            "eviction_events": self.eviction_events,
            "evictable_pages": self.tree_held,
            "oldest_tree_entry_age_s": oldest_age,
            "defers": self.defers,
            "prefix_sharing": self.tree is not None,
            # the tiered host store's occupancy/traffic picture (None
            # when no host tier is attached — serving.host_pool_bytes=0)
            "host_tier": (self.host.snapshot()
                          if self.host is not None else None),
        }
