"""ServingEngine: continuous batching over the split prefill/decode programs.

Reference analog: DeepSpeed-MII / FastGen's serving loop (continuous
batching + Dynamic SplitFuse scheduling) re-expressed for XLA's
static-shape world. The engine owns three device assets:

- a slot state (``slots.py``): ONE persistent (L, slots, KV, max_len, hd)
  KV cache plus per-slot length/tok/rng/done vectors, advanced by ONE
  compiled decode-step program regardless of which requests occupy it;
- a prefill lane: per-request chunked prefill through shape-bucketed
  chunk programs (every chunk is ``prefill_chunk`` tokens or a power-of-two
  bucket below it), at most one chunk per iteration so running requests'
  TPOT is never stalled by a long prompt;
- one insert program that writes a finished prefill into its slot
  (donated ``dynamic_update_slice`` — in place, full slot extent).

Steady state therefore compiles a BOUNDED program set — decode step +
insert + (2 x bucket count) prefill programs — and ``compiles`` counts
every build so the bench smoke test can assert no compilation happens
after warmup. Outputs are bit-identical to single-request
``generate(request_seeds=[seed], cache_len=max_len)``: per-request RNG
chains are folded from the request seed (never the slot or batch
position), and the decode step is literally the same ``decode_step`` the
static path scans.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..inference.config import ServingConfig
from ..inference.decode import (GenCarry, decode_step, forward_with_cache,
                                init_cache)
from ..inference.engine import InferenceEngine
from ..inference.sampling import per_request_keys, split_keys
from ..inference.speculation import NGramTable
from ..observability import spans as _spans
from ..observability.export import request_record
from ..observability.tracing import ServingStats
from ..resilience.chaos import ChaosMonkey
from ..resilience.guards import QueueFullError, RequestStatus
from ..utils.logging import warning_once
from .pages import (PagePool, export_slot, hydrate_cache, import_slot,
                    init_paged_slots, insert_paged)
from .scheduler import Request, Scheduler
from .slots import init_slots, insert_request

# Serving programs kept per engine; generously above the steady-state set
# (decode step + insert + 2 programs per chunk bucket) so eviction means a
# config bug, not normal traffic.
_MAX_PROGRAMS = 64
# Finished requests retained for pop_result(); a long-running server that
# never collects results must not leak host memory without bound.
_MAX_RESULTS = 4096
# health() reports degraded for this many iterations after a watchdog
# stall, then recovers — one slow step during warmup must not mark the
# replica unhealthy forever (the cumulative stall COUNT never resets).
_DEGRADED_WINDOW = 64


def expand_per_request(v, n: int, default, coerce=None) -> list:
    """One scalar-or-per-request ``serve_batch`` argument expanded to
    ``n`` values (shared by ``ServingEngine`` and ``FleetEngine`` so the
    two surfaces cannot drift on coercion/validation). ``coerce`` (e.g.
    ``int``) applies to every non-None value; None skips coercion —
    session ids keep their caller type."""
    if v is None:
        vals = [default] * n
    elif isinstance(v, (list, tuple, np.ndarray)):
        if len(v) != n:
            raise ValueError(f"expected {n} per-request values, "
                             f"got {len(v)}")
        vals = list(v)
    else:
        vals = [v] * n
    if coerce is not None:
        vals = [x if x is None else coerce(x) for x in vals]
    return vals


class ServingEngine:
    """submit()/step()/drain() continuous batching on an InferenceEngine.

    ``engine`` supplies params, mesh, model, dtype, quantization and eos;
    ``serving`` (a :class:`~..inference.config.ServingConfig` or dict)
    supplies slots/max_len/prefill_chunk and the sampling policy. Serve/*
    load metrics land in ``stats.registry`` — pass ``registry`` to share
    one registry with the engine's request tracer, and ``clock`` to fake
    time in tests.
    """

    def __init__(self, engine: InferenceEngine,
                 serving: ServingConfig | dict | None = None,
                 registry=None, clock=None, programs=None, rid_source=None,
                 name: str = ""):
        self.engine = engine
        # fleet seams (serving/fleet.py): ``programs`` shares ONE compiled
        # program cache across replicas of the same InferenceEngine (a
        # joining replica warms from it — elasticity never compile-storms),
        # ``rid_source`` shares one request-id namespace so a rid names a
        # request fleet-wide, ``name`` labels this replica in fleet
        # metrics. All None/"" on the single-engine path — behavior is
        # byte-identical to the pre-fleet engine.
        self.name = name
        if serving is None:
            serving = engine.config.serving
        self.cfg = ServingConfig.from_any(serving)
        self.model = engine.model
        mcfg = self.model.cfg
        if getattr(mcfg, "pos_embedding", None) == "learned" \
                and self.cfg.max_len > mcfg.max_seq:
            raise ValueError(
                f"serving max_len={self.cfg.max_len} exceeds the model's "
                f"learned-position table (max_seq={mcfg.max_seq})")
        self._flash = engine.config.flash_decode_resolved()
        if self._flash and self.cfg.max_len % 128 != 0:
            raise ValueError(
                f"flash_decode needs max_len to be a multiple of 128 "
                f"(Pallas lane blocks), got {self.cfg.max_len} — round up "
                "or set flash_decode=False")
        self._eos = engine.config.eos_token_id
        self._sampler = engine._sampler(self.cfg.temperature, self.cfg.top_k,
                                        self.cfg.top_p, self.cfg.greedy)
        self._mat = engine._materialized if engine.config.quantize else None
        # ---- self-speculative decoding (inference/speculation.py,
        # docs/SERVING.md): per-slot n-gram prompt-lookup drafts verified
        # by ONE fixed-shape length-(max_draft+1) forward per step. None
        # (default) leaves the decode lane the plain one-token step —
        # same program set, bit-identical behavior.
        self._spec = None
        sp = self.cfg.speculation
        if sp is not None and sp.enabled:
            if not (self.cfg.greedy or self.cfg.temperature == 0.0):
                raise ValueError(
                    "speculation requires greedy sampling (serving.greedy "
                    "= True or temperature = 0): the parity guarantee is "
                    "argmax chaining — stochastic sampling cannot be "
                    "verified against a draft bit-exactly")
            if self._flash:
                raise ValueError(
                    "speculation requires flash_decode off: the verify "
                    "forward runs the dense cache attention (T > 1), and "
                    "greedy parity is guaranteed only when the plain step "
                    "uses the same kernel")
            self._spec = sp
        # slot -> [rid, NGramTable, tokens_fed]: the per-slot drafter
        # state, lazily (re)built from prompt + emitted history so
        # placement, fleet adoption, and plain-step fallbacks all stay
        # in sync without hooks
        self._spec_tables: dict = {}
        self._spec_steps = 0           # verify forwards run
        self._spec_proposed = 0        # draft tokens proposed
        self._spec_accepted = 0        # draft tokens accepted
        self._spec_first_scored = 0    # slots with a non-empty draft
        self._spec_first_hits = 0      # ... whose first draft hit
        # decode-lane totals, BOTH lanes: emitted/slot-steps is the
        # accepted-tokens-per-step the goodput rollup and benches report
        # (exactly 1.0 when speculation is off)
        self._decode_slot_steps = 0
        self._decode_emitted = 0
        kw = {"clock": clock} if clock is not None else {}
        self.stats = ServingStats(registry=registry, **kw)
        # quantized TP decode collective (inference.tp_comm_quant): the
        # knob lives on the InferenceEngine — the shared decode step
        # carries it into every serving program automatically — but
        # serving surfaces it as a gauge so /metrics and the capacity
        # report can tell a quantized-wire replica from an fp one.
        self._tp_quant = int(getattr(engine.config, "tp_comm_quant", 0)
                             or 0)
        if self._tp_quant:
            self.stats.registry.gauge("Serve/tp_quant_bits").set(
                float(self._tp_quant))
        # ---- observability: spans / flight / SLO (docs/OBSERVABILITY.md).
        # All default-off; disabled they cost the hot path `is not None`
        # checks only — no clock reads, no syncs, no programs.
        self.spans: Optional[_spans.SpanRecorder] = None
        if self.cfg.spans:
            self.spans = _spans.SpanRecorder(self.cfg.spans_ring,
                                             clock=self.stats.clock)
        self.flight = None
        if self.cfg.flight_dir is not None:
            from ..observability.flight import FlightRecorder

            self.flight = FlightRecorder(
                self.cfg.flight_dir, spans=self.spans,
                snapshots={"serving": self.metrics_snapshot,
                           "health": self.health},
                max_dumps=self.cfg.flight_max_dumps,
                clock=self.stats.clock, job_name="serving",
                registry=self.stats.registry)
        # traffic analytics (observability/workload.py): prefix-overlap /
        # self-speculation estimators + shape histograms on the admission
        # path. None (default) = one `is not None` per admission, nothing
        # else — no programs, no syncs (the compile-freeze gate stays the
        # acceptance test).
        self.workload = None
        if self.cfg.workload is not None and self.cfg.workload.enabled:
            from ..observability.workload import WorkloadAnalyzer

            self.workload = WorkloadAnalyzer(
                self.cfg.workload, registry=self.stats.registry,
                clock=self.stats.clock)
        # traffic capture (observability/replay.py): every admitted
        # submit + terminal result into a bounded host ring — the record
        # half of record→replay; flight dumps bundle the ring's tail so
        # an incident dir is replayable standing alone. None (default)
        # builds nothing: one `is not None` per submit/retire, zero
        # programs, zero syncs (compile-freeze gates stay the oracle).
        self.capture = None
        if self.cfg.capture:
            from ..observability.replay import TrafficCapture

            self.attach_capture(TrafficCapture(
                clock=self.stats.clock, ring=self.cfg.capture_ring,
                meta=self._capture_meta()))
        self._build_slo(self.cfg.slo)
        # goodput/badput wall-time ledger (observability/goodput.py):
        # None (default) = zero clock reads added to the loop; enabled =
        # two host clock reads per iteration, still zero programs/syncs
        self.goodput = None
        if self.cfg.goodput:
            from ..observability.goodput import GoodputLedger

            self.goodput = GoodputLedger(clock=self.stats.clock,
                                         registry=self.stats.registry,
                                         prefix="Serve")
        # arrival & scaling observatory (observability/loadscope.py):
        # None (default) = one `is not None` per submit; enabled = a
        # bounded host-side arrival ring + scrape-cadence readout math,
        # still zero programs/syncs
        self.loadscope = None
        if self.cfg.loadscope is not None:
            from ..observability.loadscope import LoadScope

            self.loadscope = LoadScope(self.cfg.loadscope,
                                       registry=self.stats.registry,
                                       clock=self.stats.clock)
        # live telemetry server (observability/server.py): started at the
        # END of __init__ when config-enabled (the state must exist
        # before a scrape can land), or explicitly via serve_telemetry()
        self.telemetry = None
        self._request_logs: list = []
        # ---- paged KV cache (serving/pages.py, docs/SERVING.md): page
        # pool + radix prefix tree + host page-table mirror. Disabled
        # (page_size=0, the default) builds none of it — the engine is
        # bit-for-bit the contiguous-slot engine, same program set.
        self._paged = self.cfg.page_size > 0
        self.pool: Optional[PagePool] = None
        self._table = None
        self._table_dirty = False
        _kvs_on = self.cfg.kvscope is not None and self.cfg.kvscope.enabled
        # demote-ahead needs the same per-entry touch clock kvscope uses
        # (tree tstamps are the session-idleness signal at block grain)
        _da_on = self.cfg.demote_ahead_idle_s > 0
        if self._paged:
            self.pool = PagePool(self.cfg.pool_pages, self.cfg.page_size,
                                 self.cfg.max_len,
                                 registry=self.stats.registry,
                                 prefix_sharing=self.cfg.prefix_sharing,
                                 # the eviction-pressure ages are the
                                 # residency observatory's opt-in; the
                                 # default pool stays clock-free
                                 clock=self.stats.clock
                                 if (_kvs_on or _da_on) else None)
            # host-authoritative page tables, mirrored into the carry on
            # change (insert seats a row, retirement clears one): steady
            # full-slot decode uploads nothing
            self._table = np.zeros(
                (self.cfg.slots, self.pool.pages_per_slot), np.int32)
            if self.flight is not None:
                # stall dumps show the pool at the moment of the stall
                self.flight.add_snapshot_provider("pages",
                                                  self.pool.snapshot)
        # tiered host KV store (serving/hostkv.py, docs/SERVING.md):
        # eviction demotes cold tree-held pages to bounded pinned host
        # memory; admission restores matched cold prefixes by async H2D
        # copy instead of recompute. None (default) builds nothing —
        # one `is not None` per admission and per eviction pass, zero
        # new programs (the compile-freeze gates stay the oracle); ON it
        # adds exactly two fixed-shape programs (demote gather, restore
        # scatter) to the bounded set.
        self.hostkv = None
        # NVMe rung below the host tier + the ranked-store coordinator
        # (serving/tiering.py): built only when serving.nvme_pool_bytes
        # is set — otherwise pool.host is the bare host store, exactly
        # the PR-14 shape
        self.nvmekv = None
        self.kvtier = None
        # demote gathers dispatched this iteration, materialized to the
        # tier at the end of step() — see _demote_pages/_drain_demotes
        self._pending_demotes: list = []
        # demote-ahead lane (cfg.demote_ahead_idle_s): prefixes staged
        # into the tier while still tree-held, so eviction under
        # pressure is a refcount drop. demote_wait_s is the measured
        # admission-path demote-blocking wall the lane exists to zero.
        self._demote_ahead = (self.cfg.demote_ahead_idle_s
                              if _da_on else None)
        self._staged_ahead: set = set()
        self.demote_wait_s = 0.0
        if self._paged and self.cfg.host_pool_bytes > 0:
            from .hostkv import HostKVTier

            self.hostkv = HostKVTier(self.cfg.host_pool_bytes,
                                     self.cfg.page_size,
                                     registry=self.stats.registry,
                                     clock=self.stats.clock)
            self.pool.host = self.hostkv
            if self.cfg.nvme_pool_bytes > 0:
                from .tiering import NVMeKVTier, TieringEngine

                self.nvmekv = NVMeKVTier(self.cfg.nvme_pool_bytes,
                                         self.cfg.page_size,
                                         path=self.cfg.nvme_path,
                                         registry=self.stats.registry,
                                         clock=self.stats.clock)
                self.kvtier = TieringEngine([self.hostkv, self.nvmekv])
                self.pool.host = self.kvtier
            self.pool.on_demote = self._demote_pages
            if self.flight is not None:
                self.flight.add_snapshot_provider("host_kv",
                                                  self.hostkv.snapshot)
                if self.nvmekv is not None:
                    self.flight.add_snapshot_provider(
                        "nvme_kv", self.nvmekv.snapshot)
        # KV residency observatory (observability/kvscope.py,
        # docs/OBSERVABILITY.md): ghost-tree eviction-regret ledger on
        # the page pool + per-session lifecycle heat tracking + the
        # measured host-tier advisor inputs. None (default) builds
        # nothing — one `is not None` per admission/retirement and one
        # on the pool's eviction path; zero programs, zero syncs (the
        # compile-freeze gates stay the acceptance tests).
        self.kvscope = None
        if _kvs_on:
            from ..observability.capacity import kv_cache_bytes
            from ..observability.kvscope import KVScope

            ptb = None
            if self._paged:
                ptb = kv_cache_bytes(
                    mcfg, self.cfg.slots, self.cfg.max_len,
                    engine.compute_dtype, page_size=self.cfg.page_size,
                    pool_pages=self.cfg.pool_pages,
                    kv_quant_bits=self.cfg.kv_quant_bits,
                )["per_token_bytes"]
            pool = self.pool
            self.kvscope = KVScope(
                self.cfg.kvscope, registry=self.stats.registry,
                clock=self.stats.clock, spans=self.spans,
                page_size=self.cfg.page_size, per_token_bytes=ptb,
                # pool truth for "reclaimable now": idle-session sums
                # are capped at the tree's live residency
                tree_held_tokens=(
                    (lambda: pool.tree_held * self.cfg.page_size)
                    if pool is not None else None))
            if self.pool is not None:
                self.pool.on_evict = self.kvscope.on_evictions
            if self.flight is not None:
                self.flight.add_snapshot_provider("kv_residency",
                                                  self.kvscope.snapshot)
        # Per-tenant cost attribution & fairness observatory
        # (observability/tenantscope.py, docs/OBSERVABILITY.md): a
        # ledger keyed by Request.tenant_id on the injectable clock —
        # tokens/latency at the retirement funnel, KV page-seconds
        # through the pool's on_pages hook, resident tier bytes through
        # TierStore owner accounting, Jain fairness + the edge-triggered
        # noisy-neighbor detector (flight why-marker + incident
        # breakdown artifact). None (default) builds nothing — one
        # `is not None` per submit/admission/retirement, zero programs,
        # zero syncs (the compile-freeze gates stay the oracle).
        self.tenantscope = None
        if self.cfg.tenantscope is not None and self.cfg.tenantscope.enabled:
            from ..observability.tenantscope import TenantScope

            self.tenantscope = TenantScope(
                self.cfg.tenantscope, registry=self.stats.registry,
                clock=self.stats.clock, flight=self.flight,
                page_size=self.cfg.page_size)
            if self.pool is not None:
                self.pool.on_pages = self.tenantscope.on_pages
            if self.flight is not None:
                # every flight/incident dump carries the per-tenant
                # breakdown — the noisy-neighbor episode's evidence
                self.flight.add_artifact_provider(
                    "tenant_breakdown.json",
                    self.tenantscope.breakdown_text)
        self.sched = Scheduler(self.cfg.slots, self.cfg.max_len,
                               self.cfg.prefill_chunk,
                               max_queue=self.cfg.max_queue,
                               eos_token_id=self._eos, stats=self.stats,
                               ttft_deadline_s=self.cfg.ttft_deadline_s,
                               total_deadline_s=self.cfg.total_deadline_s,
                               spans=self.spans, pages=self.pool,
                               rid_source=rid_source)
        self._programs: OrderedDict = \
            programs if programs is not None else OrderedDict()
        # disaggregated-serving hook (serving/fleet.py): a side-effecting
        # callback invoked right after a prefill lands in a slot with
        # (req, slot). The fleet's handler takes the request over INSIDE
        # the call (export_request + release_request), so by the time
        # this step reaches its decode lane the request is gone; the
        # return value is ignored. None (default) costs one `is not
        # None` per placement.
        self.on_placed = None
        self.compiles = 0        # program builds — bounded in steady state
        # finished requests awaiting pickup, BOUNDED (oldest evicted): a
        # server whose caller consumes step()'s return values — or
        # pop_result() — never grows this; one that ignores results still
        # can't leak without bound
        self.results: OrderedDict[int, Request] = OrderedDict()
        self._max_results = _MAX_RESULTS
        # (request, chunk plan, next chunk idx, device prefill cache, rng)
        self._prefill = None
        # resilience state: chaos only exists when explicitly enabled —
        # disabled serving carries a single `is not None` check per step
        self.chaos: Optional[ChaosMonkey] = None
        if self.cfg.chaos is not None and self.cfg.chaos.enabled:
            self.chaos = ChaosMonkey(self.cfg.chaos)
        self._draining = False
        self._any_deadlines = False
        self._last_step_s = 0.0
        self._last_stall_iter: Optional[int] = None
        self._iterations = 0
        with self.engine.mesh:
            if self._paged:
                self._state = self._prog("init_slots", lambda: jax.jit(
                    lambda: init_paged_slots(
                        mcfg, self.cfg.slots, self.cfg.max_len,
                        self.cfg.page_size, self.cfg.pool_pages,
                        engine.compute_dtype, self.cfg.kv_quant_bits)))()
            else:
                self._state = self._prog("init_slots", lambda: jax.jit(
                    lambda: init_slots(mcfg, self.cfg.slots,
                                       self.cfg.max_len,
                                       engine.compute_dtype)))()
        tcfg = self.cfg.telemetry
        if tcfg is not None and tcfg.enabled:
            self.serve_telemetry(port=tcfg.port, host=tcfg.host,
                                 token=tcfg.token)

    def _build_slo(self, slo) -> None:
        """(Re)build the SLO scorer + anomaly detectors from a
        :class:`~..observability.slo.SLOConfig` (or None). Shared by
        __init__ and the live ``/slo/reload`` control endpoint."""
        self.slo = None
        self._step_anomaly = None
        self._compile_storm = None
        if slo is not None and slo.any_enabled:
            from ..observability.slo import (CompileStormDetector,
                                            MedianMADDetector, SLOScorer)

            self.slo = SLOScorer(slo, self.stats.registry,
                                 flight=self.flight)
            if slo.step_time_mad_k:
                self._step_anomaly = MedianMADDetector(
                    slo.step_time_mad_k, slo.step_time_window,
                    slo.step_time_min_samples)
            if slo.compile_storm_threshold:
                self._compile_storm = CompileStormDetector(
                    slo.compile_storm_threshold, slo.compile_storm_window,
                    slo.compile_storm_grace)

    def reload_slo(self, cfg) -> dict:
        """Swap the SLO config live (the ``POST /slo/reload`` hook): a
        None/empty ``cfg`` tears the scoring machinery down, a dict
        builds it exactly as __init__ would. Burn gauges and the
        violation counter carry over (same registry); detectors restart
        with fresh windows. Raises ``ValueError`` on unknown keys — the
        endpoint maps that to a 400, nothing half-applies."""
        import dataclasses as _dc

        from ..observability.slo import SLOConfig

        slo = SLOConfig.from_any(cfg) if cfg else None
        self.cfg.slo = slo
        self._build_slo(slo)
        return {"reloaded": True, "enabled": self.slo is not None,
                "slo": _dc.asdict(slo) if slo is not None else None}

    def _capture_meta(self) -> dict:
        """Trace-header meta via the ONE shared builder
        (:func:`~..observability.replay.capture_meta`) — the recorded
        config a faithful replay must match."""
        from ..observability.replay import capture_meta

        return capture_meta(self.cfg, engine=self.name or "serving")

    def attach_capture(self, capture) -> None:
        """Adopt a :class:`~..observability.replay.TrafficCapture` (the
        config path builds one automatically when ``serving.capture`` is
        set; tests and benches may attach their own). When a flight
        recorder exists, the capture ring's tail rides every dump as
        ``traffic_trace.jsonl``."""
        self.capture = capture
        if self.flight is not None and capture is not None:
            self.flight.add_artifact_provider("traffic_trace.jsonl",
                                              capture.tail_text)

    def _flush_table(self) -> None:
        """Mirror the host page tables into the decode carry when they
        changed (a row seated at insert, or cleared at retirement before
        its pages can be reused). A handful of int32s per event — steady
        full-slot decode uploads nothing."""
        if self._table_dirty:
            c = self._state.cache
            self._state = self._state._replace(
                cache=c._replace(page_table=jnp.asarray(self._table)))
            self._table_dirty = False

    # ----------------------------------------------------------- programs
    def _prog(self, key, build):
        """InferenceEngine._cached's bounded LRU + a compile counter
        (every build is one XLA compilation — the smoke test asserts the
        count freezes after warmup)."""
        def counted():
            self.compiles += 1
            return build()

        return InferenceEngine._cached(self._programs, key, counted,
                                       cap=_MAX_PROGRAMS)

    def _chunk_impl(self, params, cache, ids, start):
        """Intermediate prefill chunk: extend the request cache; the head
        is never computed (nothing consumes the logits, XLA removes it)."""
        cache = cache._replace(length=start)
        mat = self._mat if self._mat is not None else (lambda p: p)
        _, cache = forward_with_cache(self.model, mat(params), ids, cache)
        return cache

    def _final_impl(self, params, cache, ids, start, last_index, true_len,
                    rng):
        """Final prefill chunk: extend the cache AND sample the first token
        from the last real position (``last_index`` — right-padded buckets
        put it before the chunk end), leaving the cache at ``true_len``."""
        cache = cache._replace(length=start)
        mat = self._mat if self._mat is not None else (lambda p: p)
        logits, cache = forward_with_cache(
            self.model, mat(params), ids, cache, last_token_head=True,
            last_index=last_index)
        rng, sub = split_keys(rng)
        tok = self._sampler(logits[:, -1], sub)
        done = (tok == self._eos) if self._eos is not None \
            else jnp.zeros(tok.shape, bool)
        return GenCarry(tok=tok, cache=cache._replace(length=true_len),
                        rng=rng, done=done)

    def _step_impl(self, params, carry):
        # logit_guard: the (B,) per-row finiteness flags ride the step's
        # existing fused read-back — the guard costs zero extra host syncs
        return decode_step(self.model, params, carry, sampler=self._sampler,
                           eos_token_id=self._eos, flash_decode=self._flash,
                           logit_guard=True)

    def _step_chaos_impl(self, params, carry, poison_row):
        """Chaos build of the step: identical program + a traced poison-row
        scalar (-1 = clean; `where` on a false mask is bit-exact), so one
        compiled program covers every iteration of a chaos run."""
        return decode_step(self.model, params, carry, sampler=self._sampler,
                           eos_token_id=self._eos, flash_decode=self._flash,
                           logit_guard=True, poison_row=poison_row)

    # --------------------------------------------------- self-speculation
    def _spec_verify_impl(self, params, carry, drafts):
        """The fixed-shape verify forward: every slot's carried token +
        its (zero-padded) drafts run as ONE length-(max_draft + 1) call
        through the same ``forward_with_cache`` the chunked prefill uses
        — acceptance counts are host-side data, so this is the only
        decode-side shape speculation ever compiles. ``argmax`` over the
        fp32 logits IS the greedy sampler (``sample_logits`` with
        ``greedy=True``), so position j's winner is bit-identical to the
        token the plain step would sample after committing positions
        < j. Raw (possibly WOQ-quantized) params, exactly like
        ``_step_impl`` — the verify logits must match the plain step's
        bitwise. The per-row finiteness flags ride the same fused
        read-back as the winners (logit_guard discipline)."""
        ids = jnp.concatenate([carry.tok[:, None], drafts], axis=1)
        logits, cache = forward_with_cache(self.model, params, ids,
                                           carry.cache)
        m = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        ok = jnp.all(jnp.isfinite(logits), axis=(1, 2))
        return m, ok, carry._replace(cache=cache)

    def _spec_commit_impl(self, carry, packed):
        """Resolve the host-side acceptance: active rows rewind their
        cache length to the committed extent (rejected drafts' KV past
        it is dead by length — every future append overwrites position
        == committed length first) and take their new carry token / done
        flag. Inactive rows (idle slots, nonfinite-retired rows) keep
        the verify step's values, mirroring how plain steps advance idle
        rows — insert resets them either way.

        ``packed`` is one (4, slots) int32 — active / new_len / new_tok /
        new_done rows — so the commit costs a single host->device upload
        per step instead of four."""
        active = packed[0].astype(bool)
        new_len, new_tok = packed[1], packed[2]
        new_done = packed[3].astype(bool)
        cache = carry.cache
        length = jnp.where(active, new_len, cache.length)
        tok = jnp.where(active, new_tok, carry.tok)
        done = jnp.where(active, new_done, carry.done)
        return carry._replace(tok=tok, done=done,
                              cache=cache._replace(length=length))

    def _spec_plan(self):
        """Build this step's draft matrix from the per-slot n-gram
        tables, or None to fall back to the plain step. Host-side only.

        Fallbacks: (a) headroom — EVERY occupied slot must fit the full
        verify write extent (``live + max_draft + 1 <= max_len``),
        because both cache layouts clamp out-of-range writes in ways
        that would fold onto live positions; (b) no slot drafted
        anything — a verify forward with zero drafts is a plain step at
        (max_draft + 1)x the FLOPs.

        Drafter tables sync lazily against ``prompt + tokens`` (the
        ``tokens_fed`` watermark), so plain-step fallbacks, fleet
        adoption, and requeues need no hooks."""
        spec = self._spec
        K = spec.max_draft
        running = self.sched.running
        tables = self._spec_tables
        for slot in list(tables):
            req = running.get(slot)
            if req is None or tables[slot][0] != req.rid:
                del tables[slot]
        drafts = np.zeros((self.cfg.slots, K), np.int32)
        lens = np.zeros(self.cfg.slots, np.int32)
        any_draft = False
        for slot, req in running.items():
            P = len(req.prompt)
            total = P + len(req.tokens)
            if total - 1 + K + 1 > self.cfg.max_len:
                return None
            ent = tables.get(slot)
            if ent is None:
                tab = NGramTable(spec.ngram)
                tab.extend(np.asarray(req.prompt).reshape(-1).tolist())
                tab.extend(req.tokens)
                tables[slot] = [req.rid, tab, total]
            else:
                tab = ent[1]
                if ent[2] < total:
                    tab.extend(req.tokens[ent[2] - P:])
                    ent[2] = total
            cap = min(K, req.max_new - len(req.tokens) - 1)
            if cap <= 0:
                continue
            d = tables[slot][1].draft(cap)
            if d:
                drafts[slot, :len(d)] = d
                lens[slot] = len(d)
                any_draft = True
        return (drafts, lens) if any_draft else None

    def _spec_verify_commit(self, plan):
        """Run the verify forward, resolve per-slot acceptance host-side,
        and commit the accepted extents — the speculative decode lane's
        device work, all inside the caller's watchdog window. ONE fused
        read-back (winners + finiteness flags), same discipline as the
        plain step's. Returns ``(emitted, bad, tallies)`` for
        :meth:`_spec_resolve` to feed the scheduler AFTER the timing
        bookkeeping, exactly where ``on_step`` runs in the plain lane."""
        drafts, lens = plan
        ver = self._prog("spec_verify", lambda: jax.jit(
            self._spec_verify_impl, donate_argnums=(1,)))
        m_dev, ok_dev, self._state = ver(self.engine.params, self._state,
                                         jnp.asarray(drafts))
        m, vok = jax.device_get((m_dev, ok_dev))
        eos = self._eos
        B = self.cfg.slots
        # rows: active, new_len, new_tok, new_done — one packed upload
        packed = np.zeros((4, B), np.int32)
        active, new_len, new_tok, new_done = packed
        emitted: dict = {}
        bad: list = []
        proposed = accepted = first_scored = first_hits = 0
        for slot, req in self.sched.running.items():
            if not bool(vok[slot]):
                bad.append(slot)
                continue
            dlen = int(lens[slot])
            toks = [int(m[slot, 0])]
            j = 0
            # the acceptance chain: draft j survives iff it equals the
            # verified winner at j-1 (then winner j is the next plain
            # token); stop at the first miss or at eos — emissions past
            # eos would diverge from the plain lane's retirement
            while j < dlen and (eos is None or toks[-1] != eos) \
                    and int(drafts[slot, j]) == toks[-1]:
                toks.append(int(m[slot, j + 1]))
                j += 1
            proposed += dlen
            accepted += j
            if dlen:
                first_scored += 1
                if int(drafts[slot, 0]) == toks[0]:
                    first_hits += 1
            live = len(req.prompt) + len(req.tokens) - 1
            active[slot] = True
            new_len[slot] = live + len(toks)
            new_tok[slot] = toks[-1]
            new_done[slot] = eos is not None and toks[-1] == eos
            emitted[slot] = toks
        com = self._prog("spec_commit", lambda: jax.jit(
            self._spec_commit_impl, donate_argnums=(0,)))
        self._state = com(self._state, jnp.asarray(packed))
        return emitted, bad, (proposed, accepted, first_scored, first_hits)

    def _spec_resolve(self, spec_out) -> list:
        """Scheduler + metrics half of the speculative lane: retire
        nonfinite rows first (before their garbage could be appended),
        commit every surviving slot's emissions (page-table rollback for
        paged retirements happens inside ``on_spec_step``), and account
        the step."""
        emitted, bad, (proposed, accepted, first_scored, first_hits) = \
            spec_out
        finished: list = []
        if bad:
            finished += self.sched.retire_nonfinite(bad)
            for slot in bad:
                self._spec_tables.pop(slot, None)
        n_emitted = sum(len(t) for t in emitted.values())
        self._decode_slot_steps += len(emitted) + len(bad)
        self._decode_emitted += n_emitted
        finished += self.sched.on_spec_step(emitted)
        self._spec_steps += 1
        self._spec_proposed += proposed
        self._spec_accepted += accepted
        self._spec_first_scored += first_scored
        self._spec_first_hits += first_hits
        r = self.stats.registry
        r.counter("Serve/spec_steps").inc()
        r.counter("Serve/spec_draft_tokens").inc(proposed)
        r.counter("Serve/spec_accepted_tokens").inc(accepted)
        r.counter("Serve/spec_emitted_tokens").inc(n_emitted)
        if self.workload is not None:
            self.workload.on_spec(proposed, accepted, n_emitted,
                                  first_scored, first_hits)
        return finished

    def spec_snapshot(self) -> Optional[dict]:
        """Live speculation readout (None when the lane is off): the
        accepted-tokens-per-step multiple over BOTH lanes (plain steps
        count 1 token per slot, so the ratio is the wall-clock decode
        multiple), the draft acceptance rates, and the raw tallies the
        fleet rollup sums."""
        if self._spec is None:
            return None
        steps = self._decode_slot_steps
        return {
            "ngram": self._spec.ngram,
            "max_draft": self._spec.max_draft,
            "verify_steps": self._spec_steps,
            "proposed_tokens": self._spec_proposed,
            "accepted_tokens": self._spec_accepted,
            "slot_steps": steps,
            "emitted_tokens": self._decode_emitted,
            "accepted_tokens_per_step":
                (self._decode_emitted / steps) if steps else None,
            "accept_rate":
                (self._spec_accepted / self._spec_proposed)
                if self._spec_proposed else None,
            "first_accept_rate":
                (self._spec_first_hits / self._spec_first_scored)
                if self._spec_first_scored else None,
        }

    # ------------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               seed: int = 0, ttft_deadline_s: Optional[float] = None,
               total_deadline_s: Optional[float] = None,
               session_id=None, tenant_id=None) -> int:
        """Queue one request; returns its request id. Tokens sample with
        a per-request RNG folded from ``seed`` — bit-identical (up to eos
        truncation) to ``engine.generate(prompt[None], max_new,
        request_seeds=[seed], cache_len=<serving max_len>, ...)`` with the
        same sampling knobs; ``cache_len`` must match because the cache
        width is part of the sampled bit-stream.

        ``ttft_deadline_s`` / ``total_deadline_s`` override the config
        defaults for this request (0 disables); ``session_id`` (opaque,
        hashable) keys session-lifecycle tracking (kvscope / workload)
        and fleet affinity; ``tenant_id`` (optional string, default
        ``"default"``) is the cost-attribution dimension
        (observability/tenantscope.py). Raises
        :class:`~..resilience.guards.QueueFullError` (status ``SHED``)
        when the queue is at ``max_queue`` or the engine is draining."""
        if self._draining:
            self.stats.on_shed(self.sched.queue_depth)
            if self.tenantscope is not None:
                self.tenantscope.on_shed(tenant_id)
            raise QueueFullError("serving engine is draining; request shed",
                                 queue_depth=self.sched.queue_depth,
                                 max_queue=self.cfg.max_queue)
        max_new = int(max_new_tokens or self.engine.config.max_out_tokens)
        try:
            req = self.sched.submit(prompt, max_new, seed,
                                    ttft_deadline_s=ttft_deadline_s,
                                    total_deadline_s=total_deadline_s,
                                    session_id=session_id,
                                    tenant_id=tenant_id)
        except QueueFullError:
            # typed shed (queue full / pool can never fit it): billed to
            # the tenant even though no Request object exists yet
            if self.tenantscope is not None:
                self.tenantscope.on_shed(tenant_id)
            raise
        if req.deadline_ttft is not None or req.deadline_total is not None:
            self._any_deadlines = True
        if self.capture is not None:
            # record the OVERRIDES as passed (None = config default), so
            # replay under the same config reproduces deadline semantics
            self.capture.on_submit(req, ttft_deadline_s=ttft_deadline_s,
                                   total_deadline_s=total_deadline_s)
        if self.loadscope is not None:
            self.loadscope.on_submit(len(req.prompt), req.max_new,
                                     self.sched.queue_depth)
        if self.tenantscope is not None:
            self.tenantscope.on_submit(req)
        return req.rid

    def requeue(self, req: Request) -> Request:
        """Failover intake (serving/fleet.py): adopt a request whose
        replica was lost — typed ``REQUEUED`` transition via the
        scheduler, plus the engine-side deadline bookkeeping a normal
        ``submit`` would have done (the requeued request keeps its
        ORIGINAL absolute deadlines; this engine's sweep must see
        them). Bypasses ``max_queue`` and the drain gate: failover work
        is already-admitted work, not new intake."""
        self.sched.requeue(req)
        if req.deadline_ttft is not None or req.deadline_total is not None:
            self._any_deadlines = True
        if self.tenantscope is not None:
            self.tenantscope.on_requeue(req)
        return req

    def cancel(self, rid: int) -> Optional[Request]:
        """Cancel a request wherever it currently lives — queue, prefill
        lane, or decode slot. Returns the request (status ``CANCELLED``,
        also placed in ``results``) or None if it already finished / is
        unknown."""
        if self._prefill is not None and self._prefill[0].rid == rid:
            req = self._prefill[0]
            self._prefill = None
            self.sched.abort(req, RequestStatus.CANCELLED,
                             "cancelled during prefill")
        else:
            req = self.sched.cancel(rid)
        if req is not None:
            self._store_result(req)
        return req

    # ------------------------------------------------------------ serving
    def step(self) -> list[Request]:
        """One serving iteration: deadline sweep + <= 1 prefill chunk + 1
        decode step over the occupied slots. Returns requests that
        finished this iteration — normally (status ``OK``) or through a
        guard (``TIMEOUT`` / ``NONFINITE``); all are also kept in
        ``results``. Chaos disabled adds nothing to the device work and
        no host syncs beyond the step's one fused read-back."""
        finished: list[Request] = []
        ran_chunk = ran_decode = False
        stall_excess = 0.0
        gp = self.goodput
        if gp is not None:
            # the iteration window: two clock reads (entry/exit) — the
            # ledger's whole hot-path cost; None (default) pays nothing
            gp_t0 = gp.clock()
            gp_compiles0 = self.compiles
        chaos = self.chaos
        if chaos is not None:
            it = chaos.on_iteration()
            if it == 0 and chaos.cfg.flood_submits:
                self._chaos_flood(chaos.cfg.flood_submits)
        # deadline sweep FIRST: an expired queued request never spends a
        # prefill chunk, an expired running one frees its slot for this
        # very iteration's admission. _any_deadlines means some live or
        # past request carried one — a deadline-free server never pays
        # the sweep (or its clock read)
        if self._any_deadlines:
            finished += self._expire_deadlines()
        with self.engine.mesh:
            if self._paged:
                # retired rows cleared last iteration must reach the
                # device BEFORE their pages can be reused by this
                # iteration's admission or written by this decode step
                self._flush_table()
            # admission: start the head-of-queue request's prefill
            if self._prefill is None:
                req = self.sched.pop_next()
                if req is not None:
                    wa = None
                    if self.workload is not None:
                        # admission hook: score the prompt's prefix overlap
                        # / self-speculation potential (host-side only)
                        wa = self.workload.on_admit(req.prompt,
                                                    session_id=req.session_id)
                    if self.tenantscope is not None:
                        # partition the same estimate by tenant (prompt
                        # tokens, shared-prefix overlap)
                        self.tenantscope.on_admit(req, workload=wa)
                    if self.kvscope is not None:
                        # residency probe beside it: ghost-tree regret
                        # match + session resume edge (host-side only)
                        self.kvscope.on_admit(req)
                    cache = self._prog("init_cache", lambda: jax.jit(
                        lambda: init_cache(self.model.cfg, 1,
                                           self.cfg.max_len,
                                           self.engine.compute_dtype)))()
                    alloc = req.page_alloc
                    if alloc is not None and alloc.hydrate_pages > 0:
                        # prefix sharing: gather the shared pages into
                        # the prefill cache ONCE; the chunk plan then
                        # recomputes only the unshared suffix
                        hyd = self._prog("hydrate", lambda: jax.jit(
                            hydrate_cache, donate_argnums=(1,)))
                        cache = hyd(self._state, cache,
                                    jnp.asarray(alloc.hydrate_row),
                                    jnp.int32(alloc.hydrate_pages))
                    if alloc is not None and alloc.restored:
                        # host-tier restore: the pending-restore lane
                        # beside the prefill lane — scatter the cold
                        # blocks' tiles into the prefill cache; the
                        # suffix chunks dispatched next overlap the H2D
                        cache = self._restore_dispatch(cache, alloc)
                    self._prefill = (req, self.sched.plan(req), 0, cache,
                                     per_request_keys([req.seed]))
            # prefill lane: one bucket-shaped chunk per iteration
            if self._prefill is not None:
                finished += self._prefill_advance()
                ran_chunk = True
            # decode lane: every occupied slot advances one token — or,
            # with speculation on, up to max_draft + 1 through one
            # fixed-shape verify forward (chaos keeps the plain step:
            # poison-row semantics are per-token)
            if self.sched.running:
                t0 = self.stats.clock()
                n_slots = len(self.sched.running)
                plan = spec_out = None
                if chaos is not None:
                    chaos.maybe_hang(it)
                    poison = chaos.poison_slot(self.sched.running.keys())
                    step = self._prog("step_chaos", lambda: jax.jit(
                        self._step_chaos_impl, donate_argnums=(1,)))
                    self._state, ok = step(self.engine.params, self._state,
                                           jnp.int32(poison))
                else:
                    if self._spec is not None:
                        plan = self._spec_plan()
                    if plan is None:
                        step = self._prog("step", lambda: jax.jit(
                            self._step_impl, donate_argnums=(1,)))
                        self._state, ok = step(self.engine.params,
                                               self._state)
                if plan is not None:
                    # verify + host acceptance + commit, all inside the
                    # watchdog window; scheduler effects deferred below
                    spec_out = self._spec_verify_commit(plan)
                else:
                    # ONE fused host read-back per iteration (tok + done +
                    # per-row logit finiteness together): the
                    # per-iteration sync is the scheduler's steering cost
                    # — don't pay it twice, and don't let the guard add a
                    # second one
                    toks, dones, oks = jax.device_get(
                        (self._state.tok, self._state.done, ok))
                t1 = self.stats.clock()
                self._last_step_s = t1 - t0
                if self.spans is not None:
                    # reuses the t0/t1 the watchdog already measures — the
                    # span layer adds no clock reads to the decode window
                    self.spans.emit(_spans.DECODE_STEP, t0, t1,
                                    step=self._iterations, slots=n_slots,
                                    **({"spec": True} if plan is not None
                                       else {}))
                wd = self.cfg.watchdog_s
                if wd and self._last_step_s > wd:
                    # rising edge: the previous iteration was healthy. A
                    # stall STORM (every step slow — threshold too low, or
                    # a degraded device) must not burn the max_dumps
                    # budget that a later terminal post-mortem (SIGTERM,
                    # nonfinite halt) will need — dump once per episode,
                    # mark every stall.
                    new_episode = self._last_stall_iter != \
                        self._iterations - 1
                    self._last_stall_iter = self._iterations
                    stall_excess = self._last_step_s - wd
                    self.stats.on_watchdog_stall(self._last_step_s, wd)
                    warning_once(
                        f"serving watchdog: a decode step exceeded "
                        f"{wd:.3f}s (see Serve/last_stall_s for the "
                        "latest measurement; further stalls only count)")
                    if self.flight is not None:
                        # the black box IS the post-mortem: stamp why,
                        # then freeze the last-N events + snapshots
                        self.flight.note("watchdog_stall", t=t1,
                                         step_s=self._last_step_s,
                                         threshold_s=wd,
                                         iteration=self._iterations)
                        if new_episode:
                            self.flight.dump("watchdog_stall")
                if self._step_anomaly is not None \
                        and self._step_anomaly.observe(self._last_step_s):
                    r = self.stats.registry
                    r.counter("Serve/step_time_regressions").inc()
                    med, mad = self._step_anomaly.stats()
                    r.gauge("Serve/step_time_baseline_s").set(med)
                    if self.flight is not None:
                        self.flight.note("step_time_regression", t=t1,
                                         step_s=self._last_step_s,
                                         median_s=med, mad_s=mad,
                                         iteration=self._iterations)
                if spec_out is not None:
                    finished += self._spec_resolve(spec_out)
                else:
                    if not oks.all():
                        # retire ONLY the poisoned rows, before on_step
                        # can append their garbage tokens; every other
                        # slot's bookkeeping (and output bits) is
                        # untouched
                        bad = [s for s in np.nonzero(~oks)[0]
                               if int(s) in self.sched.running]
                        finished += self.sched.retire_nonfinite(bad)
                    self._decode_slot_steps += n_slots
                    self._decode_emitted += len(self.sched.running)
                    finished += self.sched.on_step(toks, dones)
                ran_decode = True
        if self._demote_ahead is not None:
            # background demotion lane: stage idle tree-held pages into
            # the tier BEFORE pressure (the staged gathers drain with
            # this same iteration's batch below)
            self._demote_ahead_tick()
        if self._pending_demotes:
            # off the TTFT path: the gathers dispatched at admission
            # land in the host tier after this iteration's device work
            self._drain_demotes()
        self.stats.on_iteration(self.sched.queue_depth, self.sched.occupancy,
                                self.cfg.slots, ran_chunk, ran_decode)
        if self.spans is not None:
            self.spans.counter(queue_depth=self.sched.queue_depth,
                               occupancy=self.sched.occupancy)
        if self._compile_storm is not None:
            new = self._compile_storm.update(self._iterations, self.compiles)
            if new:
                self.stats.registry.counter("Serve/compile_storms").inc()
                warning_once(
                    f"serving compile storm: {new} new programs within "
                    f"{self._compile_storm.window} iterations after "
                    "warmup — shape drift or program-cache eviction "
                    "(see docs/SERVING.md bucket tuning)")
                if self.flight is not None:
                    self.flight.note("compile_storm", new_compiles=new,
                                     total_compiles=self.compiles,
                                     iteration=self._iterations)
        self._iterations += 1
        if gp is not None:
            gp.on_serving_iteration(
                gp_t0, gp.clock(),
                decode_s=self._last_step_s if ran_decode else 0.0,
                ran_decode=ran_decode, ran_chunk=ran_chunk,
                compiled=self.compiles > gp_compiles0,
                stall_excess_s=stall_excess, draining=self._draining,
                idle=self.sched.idle and self._prefill is None)
        for req in finished:
            self._store_result(req)
        return finished

    def _store_result(self, req: Request) -> None:
        if self._paged and req.slot >= 0 \
                and self.sched.running.get(req.slot) is None:
            # neutralize the retired slot's page-table row (scratch) so
            # its freed pages can be handed to the next admission; the
            # flush lands before any device work next iteration. Guard on
            # the slot being EMPTY, not merely not-ours: a successor
            # placed into this slot within the same step already seated
            # its own row, which must not be zeroed under it
            self._table[req.slot] = 0
            self._table_dirty = True
        if self.workload is not None:
            self.workload.on_retire(req)
        if self.kvscope is not None:
            # session idle edge: the byte-seconds-held-while-idle meter
            # starts when a session's LAST live request terminates
            self.kvscope.on_retire(req)
        if self.tenantscope is not None:
            # terminal attribution: OK retirements credit the tenant
            # with the SAME len(req.tokens) ServingStats.on_retire adds
            # to Serve/completed_tokens — per-tenant sums conserve it
            self.tenantscope.on_retire(req)
        if self.capture is not None:
            self.capture.on_result(req)
        if self._request_logs or self.flight is not None:
            rec = request_record(req)
            for sink in self._request_logs:
                sink.log_request(rec)
            if self.flight is not None:
                self.flight.on_request(rec)
        self.results[req.rid] = req
        if len(self.results) > self._max_results:
            self.results.popitem(last=False)
            self.stats.on_results_evicted()
            warning_once(
                f"serving results store hit its cap ({self._max_results}); "
                "evicting oldest finished requests — collect results via "
                "step()'s return value or pop_result() (further evictions "
                "count in Serve/results_evicted)")

    def _expire_deadlines(self) -> list[Request]:
        """One deadline sweep over queue + slots + the prefill lane."""
        now = self.stats.clock()
        expired = self.sched.expire_deadlines(now)
        if self._prefill is not None:
            req = self._prefill[0]
            if (req.deadline_ttft is not None and now >= req.deadline_ttft) \
                    or (req.deadline_total is not None
                        and now >= req.deadline_total):
                self._prefill = None
                expired.append(self.sched.abort(
                    req, RequestStatus.TIMEOUT,
                    "deadline expired during prefill"))
        return expired

    def _chaos_flood(self, n: int) -> None:
        """Chaos queue flood: slam ``n`` junk one-token submits through the
        normal intake. With ``max_queue`` set, the overflow sheds through
        QueueFullError — exactly the backpressure path under test."""
        for i in range(n):
            try:
                self.submit(np.asarray([1], np.int32), 1,
                            seed=int(self.chaos.rng.integers(1 << 30)))
            except QueueFullError:
                pass  # the shed IS the scenario; counted in Serve/shed

    def _prefill_advance(self) -> list[Request]:
        req, plan, idx, cache, rng = self._prefill
        ch = plan[idx]
        ids = jnp.asarray(ch.ids[None], jnp.int32)
        params = self.engine.params
        sp = self.spans
        ct0 = sp.clock() if sp is not None else 0.0
        att = self.sched._attempt_meta(req)
        if not ch.final:
            fwd = self._prog(("chunk", ch.size), lambda: jax.jit(
                self._chunk_impl, donate_argnums=(1,)))
            cache = fwd(params, cache, ids, jnp.int32(ch.start))
            if sp is not None:
                # dispatch wall time: honest on CPU, a lower bound where
                # the chunk overlaps the async device queue
                sp.emit(_spans.PREFILL_CHUNK, ct0, sp.clock(), rid=req.rid,
                        chunk=idx, size=ch.size, final=False, **att)
            self._prefill = (req, plan, idx + 1, cache, rng)
            return []
        fin = self._prog(("final", ch.size), lambda: jax.jit(
            self._final_impl, donate_argnums=(1,)))
        pf = fin(params, cache, ids, jnp.int32(ch.start),
                 jnp.int32(ch.last_index), jnp.int32(ch.true_len), rng)
        if sp is not None:
            sp.emit(_spans.PREFILL_CHUNK, ct0, sp.clock(), rid=req.rid,
                    chunk=idx, size=ch.size, final=True, **att)
        self._prefill = None
        first_tok = int(np.asarray(pf.tok)[0])
        if req.max_new == 1 or bool(np.asarray(pf.done)[0]):
            return [self.sched.complete_at_prefill(req, first_tok)]
        slot = self.sched.place(req, first_tok)
        # donate only the slot state: the batch-1 prefill buffers have
        # different shapes and could never alias the slot cache anyway
        if self._paged:
            alloc = req.page_alloc
            self._table[slot] = alloc.row
            self._table_dirty = True
            self._flush_table()
            ins = self._prog("insert", lambda: jax.jit(
                insert_paged, donate_argnums=(0,)))
            self._state = ins(self._state, jnp.int32(slot), pf,
                              jnp.asarray(alloc.row),
                              jnp.int32(alloc.shared))
            # the prompt's blocks are in the pool now: index them for
            # future sharing and release the copy-on-write source pin
            self.pool.on_inserted(req.rid, req.prompt)
            if self.tenantscope is not None:
                # first-writer block ownership: a later demotion of any
                # of these blocks bills its tier bytes to this tenant
                self.tenantscope.on_blocks(req)
        else:
            ins = self._prog("insert", lambda: jax.jit(
                insert_request, donate_argnums=(0,)))
            self._state = ins(self._state, jnp.int32(slot), pf)
        if self.on_placed is not None:
            # disaggregated handoff: the fleet may export the freshly
            # seated request and release the slot before this very
            # iteration's decode lane runs — a prefill replica never
            # spends a decode step on a handed-off request
            self.on_placed(req, slot)
        return []

    # ------------------------------------------------------ tiered host KV
    def _demote_pages(self, entries: list) -> None:
        """``PagePool.on_demote`` handler: DISPATCH a gather of the
        evicted full-block pages' tiles (K, V, int8 scale planes) with
        ONE fixed-shape program (row padded with the scratch page) and
        queue the result for host materialization at the END of this
        iteration (:meth:`_drain_demotes`). Dispatching here pins the
        ordering — the gather reads the pages before any later-dispatched
        insert can rewrite them (XLA executes in dispatch order and
        honors pending reads across donation) — while the blocking
        ``device_get``, the CRC stamp, and the host copies stay OFF the
        admission path, so demotion never bills the resuming request's
        TTFT.

        With demote-ahead on, pages the background lane already staged
        into the tier need NO gather at all — their eviction is the
        refcount drop that already happened in the pool; only the
        never-staged remainder pays the dispatch. The pressure-tagged
        gather-dispatch wall (and the matching ``device_get`` wall in
        :meth:`_drain_demotes`) accumulates into
        ``Serve/host_tier_demote_wait_s`` — the admission-path
        demote-blocking time the lane exists to zero (a fully staged
        eviction adds exactly nothing to it)."""
        todo = entries
        if self._demote_ahead is not None:
            from ..observability.workload import token_hash

            tier, todo, fast = self.pool.host, [], 0
            for e in entries:
                key = (len(e["tokens"]), token_hash(e["tokens"]))
                self._staged_ahead.discard(key)
                if tier.holds(e["tokens"], key=key):
                    fast += 1   # pre-staged: eviction is a pure free
                else:
                    todo.append(e)
            if fast:
                self.stats.registry.counter(
                    "Serve/demote_ahead_fastfrees").inc(fast)
                self.stats.registry.set_gauges({
                    "Serve/host_tier_staged_ahead":
                        float(len(self._staged_ahead))})
        if todo:
            self._dispatch_demote_gather(todo, pressure=True)

    def _dispatch_demote_gather(self, entries: list,
                                pressure: bool) -> None:
        """Dispatch fixed-shape gathers of ``entries``' pages (the ONE
        compiled "demote" program — the eviction path and the
        demote-ahead lane share it, so the lane adds zero programs).
        ``pressure`` tags eviction-driven batches: their dispatch wall
        here and their ``device_get`` wall at drain count as
        admission-path demote blocking; background staging's do not."""
        from .hostkv import demote_rows

        t0 = self.stats.clock() if pressure else None
        n = self.pool.pages_per_slot
        for off in range(0, len(entries), n):
            batch = entries[off:off + n]
            row = np.zeros(n, np.int32)
            row[:len(batch)] = [e["page"] for e in batch]
            prog = self._prog("demote", lambda: jax.jit(demote_rows))
            with self.engine.mesh:
                self._pending_demotes.append(
                    (prog(self._state, jnp.asarray(row)), batch,
                     pressure))
        if pressure:
            self.demote_wait_s += max(0.0, self.stats.clock() - t0)
            self.stats.registry.set_gauges({
                "Serve/host_tier_demote_wait_s": self.demote_wait_s})

    def _demote_ahead_tick(self) -> None:
        """The background demotion lane (cfg.demote_ahead_idle_s):
        tree-held full blocks idle past the threshold — per-entry touch
        stamps, the block-grain spelling of the session idleness
        kvscope's heat ledger tracks — are gathered and staged into the
        tier OFF the admission path, one ``pages_per_slot`` batch per
        iteration, oldest first. Staging is a COPY: the pages stay
        tree-held, a resuming session still takes the normal tree hit
        (wasting at most the staged copy), and tree-held pages with no
        slot users are immutable (divergence copies-on-write), so a
        staged copy can never go stale."""
        pool = self.pool
        if pool.tree_held == 0:
            return
        from ..observability.workload import token_hash

        tier = pool.host
        cutoff = self.stats.clock() - self._demote_ahead
        cand = pool.demote_ahead_candidates(cutoff, pool.pages_per_slot,
                                            skip=tier.holds)
        if not cand:
            return
        self._dispatch_demote_gather(cand, pressure=False)
        for e in cand:
            self._staged_ahead.add(
                (len(e["tokens"]), token_hash(e["tokens"])))
        self.stats.registry.counter(
            "Serve/demote_ahead_staged").inc(len(cand))
        self.stats.registry.set_gauges({
            "Serve/host_tier_staged_ahead":
                float(len(self._staged_ahead))})

    def _drain_demotes(self) -> None:
        """Materialize this iteration's dispatched demote gathers into
        the tier (one blocking ``device_get`` per batch — by now the
        gather has usually completed under the iteration's other device
        work). Runs at the end of every ``step()``; the transient
        device residency is bounded by one gather output per batch of
        one iteration. Pressure-tagged batches (reactive eviction
        demotes) bill their ``device_get`` wall to the demote-wait
        meter; demote-ahead's background staging does not."""
        pending, self._pending_demotes = self._pending_demotes, []
        pressured = False
        for out, batch, pressure in pending:
            t0 = self.stats.clock() if pressure else None
            tiles = jax.device_get(out)
            if pressure:
                self.demote_wait_s += max(0.0, self.stats.clock() - t0)
                pressured = True
            for i, e in enumerate(batch):
                self.pool.host.put(
                    e["tokens"],
                    {k: np.ascontiguousarray(v[:, i])
                     for k, v in tiles.items()},
                    # tier-byte attribution: the tenant whose request
                    # first wrote this block (None when tenantscope is
                    # off or the block predates it)
                    owner=(self.tenantscope.block_owner(e["tokens"])
                           if self.tenantscope is not None else None))
        if pressured:
            self.stats.registry.set_gauges({
                "Serve/host_tier_demote_wait_s": self.demote_wait_s})

    def _restore_dispatch(self, cache, alloc):
        """Scatter one admission's host-restored tiles into its prefill
        cache pages ``[shared, shared + restored)`` — in up to two
        fixed-shape batches, so the second H2D upload overlaps the first
        batch's device write (double-buffered), and the whole restore
        overlaps the unshared-suffix chunk programs dispatched right
        after (async dispatch, no host sync here). The cache then flows
        through the SAME chunk-plan → ``insert_paged`` path as a tree
        hit. The measured dispatch window is honest on CPU and a lower
        bound where the scatter overlaps the async device queue."""
        from .hostkv import restore_into_cache

        t0 = self.stats.clock()
        n = self.pool.pages_per_slot
        R = max(1, (n + 1) // 2)          # batch size: <= 2 dispatches
        tiles = alloc.restore_tiles
        prog = self._prog("restore", lambda: jax.jit(
            restore_into_cache, donate_argnums=(0,)))
        off = 0
        while off < alloc.restored:
            cnt = min(R, alloc.restored - off)
            batch = {}
            for key, v in tiles.items():
                pad = np.zeros(v.shape[:1] + (R,) + v.shape[2:], v.dtype)
                pad[:, :cnt] = v[:, off:off + cnt]
                batch[key] = jnp.asarray(pad)
            cache = prog(cache, batch, jnp.int32(alloc.shared + off),
                         jnp.int32(cnt))
            off += cnt
        self.pool.host.on_restore(self.stats.clock() - t0,
                                  pages=alloc.restored,
                                  tokens=alloc.restore_tokens,
                                  nbytes=alloc.restore_bytes)
        alloc.restore_tiles = None        # the payload is on device now
        return cache

    def prefix_residency(self, prompt) -> tuple:
        """``(tree_blocks, host_blocks)`` of ``prompt``'s leading full
        blocks resident on THIS engine — the fleet router's affinity
        input (tree hit ranks above host-tier hit ranks above miss).
        ``(0, 0)`` on the contiguous engine. Read-only."""
        if not self._paged:
            return (0, 0)
        return self.pool.residency(np.asarray(prompt, np.int32))

    def begin_drain(self) -> None:
        """Graceful drain mode: stop ADMITTING new submits (they shed with
        :class:`QueueFullError`, status ``SHED``) while queued and running
        requests keep being served to completion. ``health()`` reports
        ``ready: False`` so load balancers rotate the replica out."""
        self._draining = True

    def end_drain(self) -> None:
        """Reopen intake after a drain (e.g. a cancelled rollout)."""
        self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, max_iterations: int = 1_000_000) -> dict[int, Request]:
        """Graceful shutdown: enter drain mode, run until queue and slots
        are empty, return ``results``. Intake stays closed afterwards —
        call :meth:`end_drain` to reopen."""
        self.begin_drain()
        it = 0
        while not self.sched.idle or self._prefill is not None:
            self.step()
            it += 1
            if it > max_iterations:
                raise RuntimeError(
                    f"serving failed to drain in {max_iterations} "
                    "iterations — scheduler wedged?")
        return self.results

    def pop_result(self, rid: int) -> Optional[Request]:
        """Collect (and release) a finished request; None if not finished
        or already collected."""
        return self.results.pop(rid, None)

    # ------------------------------------------------- fleet handoff seams
    def export_request(self, req: Request) -> dict:
        """Gather a slot-resident request's complete decode state (pool
        page tiles + slot vectors) to HOST numpy — the source half of the
        disaggregated prefill→decode handoff (serving/fleet.py). One
        compiled program regardless of request or slot (the table row is
        data). Paged engines only."""
        if not self._paged:
            raise RuntimeError("export_request needs the paged KV cache "
                               "(set serving.page_size)")
        if req.slot < 0 or self.sched.running.get(req.slot) is not req:
            raise ValueError(f"request {req.rid} is not slot-resident here")
        with self.engine.mesh:
            self._flush_table()
            exp = self._prog("export", lambda: jax.jit(export_slot))
            out = exp(self._state, jnp.asarray(self._table[req.slot]),
                      jnp.int32(req.slot))
        return jax.device_get(out)

    def release_request(self, req: Request) -> None:
        """Drop a slot-resident request WITHOUT retiring it: free the
        slot, release its page refs (the prompt's blocks stay tree-held
        for future sharing), neutralize the table row. The request
        object itself stays live — the fleet seats it elsewhere. No
        retirement stats, no terminal span: this is a move, not an
        outcome."""
        slot = req.slot
        if slot >= 0 and self.sched.running.get(slot) is req:
            del self.sched.running[slot]
            self.sched.free.append(slot)
        self.sched._release_pages(req)
        req.page_alloc = None
        req.slot = -1
        if self._paged and slot >= 0 \
                and self.sched.running.get(slot) is None:
            self._table[slot] = 0
            self._table_dirty = True
        if self.kvscope is not None:
            # the handoff ends the session's activity on THIS replica
            # (its tree keeps the prompt blocks); without this edge a
            # prefill replica's sessions would stay ACTIVE forever
            self.kvscope.on_retire(req)

    def import_request(self, req: Request, payload: dict) -> bool:
        """Seat an exported request into THIS engine's pool and a free
        slot — the destination half of the handoff. Returns False (try
        again after a retirement) when no slot is free or the pool is
        transiently full; True when the request is decoding here. The
        imported bits continue the source's exact RNG chain, so the
        output stream is bit-identical to a single engine's."""
        if not self._paged:
            raise RuntimeError("import_request needs the paged KV cache "
                               "(set serving.page_size)")
        if not self.sched.free:
            return False
        if self.tenantscope is not None:
            # rid → tenant binding must exist BEFORE try_admit fires the
            # pool's on_pages hook, or the pages bill to "default"
            self.tenantscope.on_adopt(req)
        # book_savings=False: seating already-computed KV skips no
        # prefill — the SOURCE replica owns the savings accounting
        alloc = self.pool.try_admit(req.prompt, req.max_new, req.rid,
                                    book_savings=False)
        if alloc is None:
            return False
        # hop stamp: the import window opens now (the attempt that will
        # seat the request — failed probes above returned before work);
        # handoff_wait_s ends here, import_s covers the scatter below
        req.import_t0 = self.stats.clock()
        req.page_alloc = alloc
        slot = self.sched.adopt(req)
        if req.deadline_ttft is not None or req.deadline_total is not None:
            # this engine never saw the request's submit(): the deadline
            # sweep must still cover the imported residency
            self._any_deadlines = True
        with self.engine.mesh:
            self._table[slot] = alloc.row
            self._table_dirty = True
            self._flush_table()
            imp = self._prog("import", lambda: jax.jit(
                import_slot, donate_argnums=(0,)))
            self._state = imp(self._state, jnp.int32(slot),
                              {k: jnp.asarray(v) for k, v in payload.items()},
                              jnp.asarray(alloc.row), jnp.int32(alloc.shared))
            self.pool.on_inserted(req.rid, req.prompt)
            if self.tenantscope is not None:
                self.tenantscope.on_blocks(req)
        if self.kvscope is not None:
            # decode-side session intake: residency moves here (no
            # regret probe — this replica paid no prefill)
            self.kvscope.on_import(req)
        req.import_t1 = self.stats.clock()
        return True

    def serve_batch(self, prompts, max_new_tokens=None, seeds=None,
                    session_ids=None, tenant_ids=None) -> list:
        """Convenience: submit a list of (ragged) prompts, drain, return
        each request's tokens as an int32 array, in submission order.
        ``max_new_tokens``, ``seeds``, ``session_ids``, and
        ``tenant_ids`` may be scalars or per-request lists. Results are
        collected (popped) — repeated calls on one engine don't
        accumulate host state."""
        n = len(prompts)
        mn = expand_per_request(max_new_tokens, n, None, int)
        sd = expand_per_request(seeds, n, 0, int)
        sid = expand_per_request(session_ids, n, None)
        tid = expand_per_request(tenant_ids, n, None)
        rids = [self.submit(p, mn[i], seed=sd[i], session_id=sid[i],
                            tenant_id=tid[i])
                for i, p in enumerate(prompts)]
        want = set(rids)
        got: dict[int, Request] = {}
        it = 0
        while len(got) < n:
            for req in self.step():
                if req.rid in want:
                    got[req.rid] = req
                    self.results.pop(req.rid, None)
            it += 1
            if it > 1_000_000:
                raise RuntimeError("serve_batch failed to finish — "
                                   "scheduler wedged?")
        return [np.asarray(got[r].tokens, np.int32) for r in rids]

    # ------------------------------------------------------------ metrics
    @property
    def degraded(self) -> bool:
        """A watchdog stall within the last ``_DEGRADED_WINDOW``
        iterations (recovers once steps are healthy again; the
        cumulative stall COUNT doesn't) — one definition shared by
        :meth:`health` and the fleet router."""
        return (self._last_stall_iter is not None
                and self._iterations - self._last_stall_iter
                <= _DEGRADED_WINDOW)

    @property
    def pool_pressure(self) -> bool:
        """Paged engine with an empty free list: admissions are
        deferring or shedding — shared by :meth:`health` and the fleet
        router."""
        return self._paged and not self.pool.free

    def health(self) -> dict:
        """Liveness/readiness snapshot for probes, also exported as
        ``Serve/*`` gauges (so the Prometheus textfile carries the same
        truth the probe endpoint returns). ``ready`` means "will accept a
        submit right now": not draining and not at queue capacity.
        ``degraded`` flags a watchdog stall within the last
        ``_DEGRADED_WINDOW`` iterations — and recovers once steps are
        healthy again (the cumulative ``watchdog_stalls`` count doesn't).

        On the paged engine the snapshot also mirrors the page-pool
        picture (``pages``: free/used/tree-held + ``pool_pressure`` when
        the free list is empty — admissions are deferring or shedding),
        so ``/readyz`` reports pool-exhaustion pressure alongside the
        queue/drain state it always knew about."""
        snap = self.stats.registry.snapshot()
        stalls = int(snap["counters"].get("Serve/watchdog_stalls", 0))
        queue_full = bool(self.cfg.max_queue
                          and self.sched.queue_depth >= self.cfg.max_queue)
        degraded = self.degraded
        out = {
            "state": "draining" if self._draining else "serving",
            "ready": not self._draining and not queue_full,
            "degraded": degraded,
            "queue_depth": self.sched.queue_depth,
            "occupancy": self.sched.occupancy,
            "slots": self.cfg.slots,
            "prefill_inflight": self._prefill is not None,
            "iterations": self._iterations,
            "last_step_s": self._last_step_s,
            "watchdog_stalls": stalls,
            "results_held": len(self.results),
            "pool_pressure": False,
        }
        gauges = {
            "Serve/ready": float(out["ready"]),
            "Serve/draining": float(self._draining),
            "Serve/degraded": float(degraded),
            "Serve/last_step_s": self._last_step_s,
            # results-store depth: a caller that never collects results
            # shows up as a climbing gauge long before evictions start
            "Serve/results_held": float(len(self.results)),
        }
        if self._paged:
            ps = self.pool.snapshot()
            pressure = ps["free_pages"] == 0
            out["pages"] = {
                "free_pages": ps["free_pages"],
                "used_pages": ps["used_pages"],
                "usable_pages": ps["usable_pages"],
                "tree_held_pages": ps["tree_held_pages"],
                # eviction pressure through /readyz: what the next
                # admission under pressure would reclaim, how often
                # pressure has bitten, and how stale the coldest entry is
                "evictable_pages": ps["evictable_pages"],
                "eviction_events": ps["eviction_events"],
                "pages_evicted": ps["pages_evicted"],
                "oldest_tree_entry_age_s": ps["oldest_tree_entry_age_s"],
                "pool_pressure": pressure,
            }
            out["pool_pressure"] = pressure
            # keep the Serve/page_* gauges fresh at probe time too (the
            # pool only rewrites them on alloc/free events)
            gauges.update({
                "Serve/page_pool_free": float(ps["free_pages"]),
                "Serve/page_pool_used": float(ps["used_pages"]),
                "Serve/page_pool_tree_held": float(ps["tree_held_pages"]),
                "Serve/page_pool_pressure": float(pressure),
            })
        if self.hostkv is not None:
            # host-tier occupancy + pressure through /readyz, beside the
            # device pool's eviction-pressure fields: a full tier means
            # the next demotion starts pruning cold history (regret
            # creeps back) — ops sees it before the regret ledger does
            hs = self.hostkv.snapshot()
            out["host_tier"] = {
                "pages": hs["pages"],
                "bytes": hs["bytes"],
                "capacity_bytes": hs["capacity_bytes"],
                "occupancy": hs["occupancy"],
                "pressure": hs["pressure"],
                "restores": hs["restores"],
                "prunes": hs["prunes"],
                "fallbacks": hs["fallbacks"],
            }
            if self._demote_ahead is not None:
                out["host_tier"]["staged_ahead"] = len(self._staged_ahead)
                out["host_tier"]["demote_wait_s"] = self.demote_wait_s
            # snapshot() already refreshed the Serve/host_tier_* gauges
        if self.nvmekv is not None:
            # the disk rung beside it: occupancy, verified promotions,
            # and the two failure signals ops gates on (counted CRC
            # fallbacks, aio transport errors)
            ns = self.nvmekv.snapshot()
            out["nvme_tier"] = {
                "pages": ns["pages"],
                "bytes": ns["bytes"],
                "capacity_bytes": ns["capacity_bytes"],
                "occupancy": ns["occupancy"],
                "pressure": ns["pressure"],
                "promotions": ns["promotions"],
                "spilled_in": self.hostkv.spills,
                "fallbacks": ns["fallbacks"],
                "aio_errors": ns["aio_errors"],
                "native_aio": ns["native_aio"],
            }
        self.stats.registry.set_gauges(gauges)
        if self.loadscope is not None:
            # refresh Serve/utilization / predicted-wait / TTV at probe
            # cadence (the report sets its own gauges as a side effect)
            self.scaling_snapshot()
        return out

    def metrics_snapshot(self) -> dict:
        out = {"compiles": self.compiles, **self.stats.snapshot()}
        if self.workload is not None:
            out["workload"] = self.workload.snapshot()
        spec = self.spec_snapshot()
        if spec is not None:
            out["speculation"] = spec
        if self._paged:
            out["pages"] = self.pool.snapshot()
        if self.kvscope is not None:
            out["kv_residency"] = self.kvscope.snapshot()
        if self.goodput is not None:
            out["goodput"] = self.goodput.snapshot()
        if self.loadscope is not None:
            out["loadscope"] = self.scaling_snapshot()
        if self.tenantscope is not None:
            out["tenants"] = self.tenants_snapshot()
        return out

    def tenants_snapshot(self) -> Optional[dict]:
        """The per-tenant breakdown (``GET /tenants``, doctor's
        ``[tenants]`` section): tenantscope's report with this engine's
        tier stores attached so resident bytes split by owner. None when
        tenantscope is off."""
        if self.tenantscope is None:
            return None
        tiers = {}
        if self.hostkv is not None:
            tiers["host_tier"] = self.hostkv
        if self.nvmekv is not None:
            tiers["nvme_tier"] = self.nvmekv
        return self.tenantscope.report(tiers=tiers or None)

    def requests_table(self) -> list[dict]:
        """Live in-flight table (the ``GET /requests`` endpoint): every
        request currently queued, prefilling, or decoding — host-side
        bookkeeping only, no device reads. Reads the prefill lane
        through ONE local binding: the HTTP thread races the serving
        loop, which may clear ``_prefill`` between a check and a
        subscript."""
        p = self._prefill
        return self.sched.inflight_table(p[0] if p is not None else None)

    def _find_request(self, rid: int) -> Optional[Request]:
        """The request wherever it lives on THIS engine — results,
        prefill lane, slots, or queue; None if unknown here. Containers
        are copied before iteration: the telemetry HTTP thread calls
        this while the serving loop mutates them."""
        req = self.results.get(rid)
        if req is not None:
            return req
        p = self._prefill
        if p is not None and p[0].rid == rid:
            return p[0]
        for r in list(self.sched.running.values()):
            if r.rid == rid:
                return r
        for r in list(self.sched.queue):
            if r.rid == rid:
                return r
        return None

    def request_trace(self, rid: int) -> Optional[dict]:
        """One request's hop-latency decomposition
        (:func:`~..observability.export.hop_trace`) — finished requests
        from ``results``, live ones from the scheduler (hops completed
        so far; the rest null). None when this engine doesn't know the
        rid. Host timestamps only — no span ring required, no device
        reads."""
        from ..observability.export import hop_trace

        req = self._find_request(rid)
        if req is None:
            return None
        return {"rid": rid, "status": req.status.value,
                "finished": req.finished, "slot": req.slot,
                "tokens": len(req.tokens), "hops": hop_trace(req)}

    # ----------------------------------------------------------- capacity
    def capacity_census(self) -> dict:
        """Per-program cost census over the engine's bounded program set:
        static FLOPs / HBM bytes / collective bytes (compiler + HLO truth,
        AOT-lowered — nothing executes) joined with achieved wall times
        from the span ring (``decode_step`` / ``prefill_chunk`` spans)
        into achieved-vs-roofline MBU/MFU per program. Census rows cover
        the programs traffic has actually built: the slot decode step and
        every prefill bucket compiled so far. Backends without
        cost/memory analysis degrade rows to null fields, never raise."""
        from ..observability.capacity import ProgramCensus, roofline_peaks

        pf, bw = roofline_peaks()
        census = ProgramCensus(peak_flops=pf, peak_bw=bw)
        mesh = self.engine.mesh
        params = self.engine.params
        # only programs traffic actually built — building (and compile-
        # counting) the step here would put a phantom compile in the
        # freeze gates and feed the compile-storm detector
        if "step" in self._programs:
            census.measure("step", self._programs["step"],
                           params, self._state, mesh=mesh)
        elif "step_chaos" in self._programs:
            census.measure("step", self._programs["step_chaos"],
                           params, self._state, jnp.int32(-1), mesh=mesh)
        # prefill buckets: census exactly the chunk programs traffic
        # built (avals only — a batch-1 cache never materializes)
        cache_aval = jax.eval_shape(
            lambda: init_cache(self.model.cfg, 1, self.cfg.max_len,
                               self.engine.compute_dtype))
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        rng_aval = jax.eval_shape(lambda: per_request_keys([0]))
        for key in [k for k in self._programs
                    if isinstance(k, tuple) and k[0] in ("chunk", "final")]:
            stem, size = key
            ids = jax.ShapeDtypeStruct((1, size), jnp.int32)
            if stem == "chunk":
                census.measure(f"chunk_{size}", self._programs[key],
                               params, cache_aval, ids, i32, mesh=mesh)
            else:
                census.measure(f"final_{size}", self._programs[key],
                               params, cache_aval, ids, i32, i32, i32,
                               rng_aval, mesh=mesh)
        if self.spans is not None:
            census.attach_spans(self.spans.events())
        return census.report()

    def _prefill_rate(self) -> Optional[dict]:
        """Measured prefill throughput from the span ring's
        ``prefill_chunk`` spans (dispatch tokens / dispatch wall) — the
        recompute-cost side of the tiered_kv lever. None when spans are
        off or no chunk has run (the lever then degrades to score 0:
        unmeasured, not guessed)."""
        if self.spans is None:
            return None
        from ..observability import spans as _sp

        toks = 0
        wall = 0.0
        for ev in self.spans.events():
            if ev.kind == _sp.PREFILL_CHUNK and ev.t1 is not None:
                toks += int(ev.meta.get("size") or 0)
                wall += ev.duration
        if toks <= 0 or wall <= 0:
            return None
        return {"tokens": toks, "wall_s": wall,
                "tokens_per_s": toks / wall}

    def _decode_rate(self) -> Optional[dict]:
        """Measured decode slot-throughput from the span ring's
        ``decode_step`` spans: emitted tokens (one per active slot per
        step) over busy slot-seconds — the serviceable-rate side of the
        loadscope utilization model. None when spans are off or no
        decode step has run (ρ then degrades to unmeasured, not
        guessed)."""
        if self.spans is None:
            return None
        from ..observability import spans as _sp

        toks = 0
        slot_s = 0.0
        wall = 0.0
        steps = 0
        for ev in self.spans.events():
            if ev.kind == _sp.DECODE_STEP and ev.t1 is not None:
                n = int(ev.meta.get("slots") or 0)
                toks += n
                slot_s += ev.duration * n
                wall += ev.duration
                steps += 1
        if toks <= 0 or slot_s <= 0:
            return None
        return {"steps": steps, "tokens": toks, "wall_s": wall,
                "slot_s": slot_s, "tokens_per_slot_s": toks / slot_s,
                "tokens_per_s": toks / wall if wall > 0 else None}

    def scaling_snapshot(self) -> Optional[dict]:
        """The arrival & scaling observatory's readout (``GET /scaling``,
        the capacity report's ``loadscope`` section, one replica row of
        ``FleetEngine.scaling_report()``): arrival-process estimates
        joined with span-measured service rates into utilization ρ,
        predicted queue wait, SLO time-to-violation, and scored scaling
        what-ifs. None when loadscope is off; unmeasured inputs degrade
        field-by-field with stated reasons, never raise."""
        if self.loadscope is None:
            return None
        dec = self._decode_rate()
        pre = self._prefill_rate()
        service = {
            "slots": self.cfg.slots,
            "decode_tokens_per_slot_s": (dec or {}).get("tokens_per_slot_s"),
            "decode_tokens_per_s": (dec or {}).get("tokens_per_s"),
            "effective_concurrency": (
                dec["tokens_per_s"] / dec["tokens_per_slot_s"]
                if dec and dec.get("tokens_per_s")
                and dec.get("tokens_per_slot_s") else None),
            "prefill_tokens_per_s": (pre or {}).get("tokens_per_s"),
        }
        slo_cfg = self.slo.cfg if self.slo is not None else None
        return self.loadscope.report(service=service, slo=slo_cfg,
                                     queue_depth=self.sched.queue_depth)

    def kv_residency(self) -> Optional[dict]:
        """The KV residency observatory's readout plus the two measured
        host-tier inputs the capacity advisor joins it with: the (cached)
        host↔device copy-bandwidth probe and the span ring's measured
        prefill throughput. None when kvscope is off."""
        if self.kvscope is None:
            if self.hostkv is None:
                return None
            # no observatory, but the tier's achieved side still reports
            out = {"enabled": False, "host_tier": self.hostkv.snapshot()}
            if self.nvmekv is not None:
                out["nvme_tier"] = self.nvmekv.snapshot()
            return out
        snap = self.kvscope.snapshot()
        snap["copy_bandwidth"] = self.kvscope.copy_bandwidth()
        snap["prefill"] = self._prefill_rate()
        if self.hostkv is not None:
            # the ACHIEVED side of the tiered_kv lever: what the host
            # tier actually restored, at what measured rate — reported
            # next to the advisor's projection (observability/capacity.py)
            snap["host_tier"] = self.hostkv.snapshot()
        if self.nvmekv is not None:
            # the disk rung's achieved side (verified promotions +
            # measured read bandwidth) — the nvme sub-estimate's input
            snap["nvme_tier"] = self.nvmekv.snapshot()
        return snap

    def hbm_ledger(self, temp_bytes: Optional[int] = None) -> dict:
        """The live HBM budget decomposed (weights / KV / temp) with
        projected headroom, as ``Memory/ledger_*`` gauges in the serving
        registry — see :func:`~..observability.capacity.hbm_ledger`.
        On the paged engine the KV term is the page pool (int8 + scale
        planes when KV quantization is on) and the ledger carries the
        live used/free page decomposition instead of the contiguous
        estimate."""
        from ..observability.capacity import hbm_ledger

        paged_kw = {}
        if self._paged:
            snap = self.pool.snapshot()
            paged_kw = {"page_size": self.cfg.page_size,
                        "pool_pages": self.cfg.pool_pages,
                        "kv_quant_bits": self.cfg.kv_quant_bits,
                        "pages_used": snap["used_pages"],
                        "pages_free": snap["free_pages"]}
        if self.kvscope is not None:
            # the host-tier row: bytes reclaimable by demoting idle
            # sessions' tree-held pages at the measured idle distribution
            paged_kw["idle_kv_bytes"] = self.kvscope.idle_kv_bytes()
        if self.hostkv is not None:
            # achieved: host bytes the tier holds right now
            paged_kw["host_tier_bytes"] = self.hostkv.bytes_used
        return hbm_ledger(
            params=self.engine.params, model_cfg=self.model.cfg,
            slots=self.cfg.slots, max_len=self.cfg.max_len,
            cache_dtype=self.engine.compute_dtype, temp_bytes=temp_bytes,
            registry=self.stats.registry, **paged_kw)

    def capacity_report(self, path=None, census: bool = True,
                        commscope=None) -> dict:
        """The capacity advisor: workload analytics + HBM ledger + program
        census composed into ranked what-if estimates on the observed
        traffic (``CAPACITY_REPORT.json`` when ``path`` is given; see
        docs/OPERATIONS.md capacity-planning runbook). ``census=False``
        skips the AOT lowering pass (cheaper; advisor loses the
        collective-byte lever's input). ``commscope`` optionally carries
        a communication-observatory report (``Engine.comm_observatory``
        / ``observability/commscope.py``) — the quantize/overlap
        collectives lever then ranks on MEASURED exposed time instead of
        the byte-share projection."""
        import math as _math

        from ..observability.capacity import (capacity_report,
                                              write_capacity_report)

        cen = self.capacity_census() if census else None
        temp = None
        if cen:
            temps = [r.get("temp_bytes") for r in cen["programs"].values()]
            temps = [t for t in temps if t is not None]
            temp = max(temps) if temps else None
        ledger = self.hbm_ledger(temp_bytes=temp)
        gauges = self.stats.registry.snapshot()["gauges"]
        occ = gauges.get("Serve/slot_occupancy_avg",
                         gauges.get("Serve/slot_occupancy"))
        if isinstance(occ, float) and _math.isnan(occ):
            occ = None
        wl = self.workload.snapshot() if self.workload is not None else None
        if self._tp_quant:
            # the quantized TP decode collective is ON: the advisor's
            # quantized_collectives lever reports it as achieved (wire
            # already int8) instead of projecting the same win again
            commscope = dict(commscope) if commscope else {}
            gq = dict(commscope.get("quantized") or {})
            gq.update({"active": True, "tp_quant_bits": self._tp_quant})
            commscope["quantized"] = gq
        rep = capacity_report(
            ledger=ledger, census=cen, workload=wl, occupancy_avg=occ,
            commscope=commscope, kvscope=self.kv_residency(),
            loadscope=self.scaling_snapshot(),
            tenantscope=self.tenants_snapshot(),
            pages=self.pool.snapshot() if self._paged else None,
            meta={"job": "serving", "slots": self.cfg.slots,
                  "max_len": self.cfg.max_len,
                  "prefill_chunk": self.cfg.prefill_chunk,
                  "page_size": self.cfg.page_size,
                  "kv_quant_bits": self.cfg.kv_quant_bits,
                  "iterations": self._iterations,
                  "compiles": self.compiles})
        if path is not None:
            write_capacity_report(rep, path)
        return rep

    def score_slo(self) -> dict:
        """One SLO scoring pass (``Serve/slo_*_burn`` gauges + flight
        markers on new breaches); {} when no SLO config is set. Runs
        inside ``publish_metrics`` so a normal serving loop needs no
        extra call."""
        return self.slo.score() if self.slo is not None else {}

    def attach_monitor(self, monitor) -> None:
        """Adopt a MonitorMaster's request-log writers: every retired
        request is logged as one JSON record through the fan-out's
        ``RequestLogSink`` (config ``monitor.request_log``). Scalar
        metrics still flow via :meth:`publish_metrics` — call that on the
        loop's cadence as before."""
        for w in getattr(monitor, "writers", []):
            if hasattr(w, "log_request") and w not in self._request_logs:
                self._request_logs.append(w)

    def dump_flight(self, reason: str = "manual"):
        """Freeze the flight recorder now (ops triage / shutdown hook);
        returns the dump directory or None (no recorder / dump cap)."""
        if self.flight is None:
            return None
        return self.flight.dump(reason)

    def publish_metrics(self, monitor, step: Optional[int] = None) -> int:
        """Push ``Serve/*`` through a monitor fan-out (same contract as
        ``InferenceEngine.publish_metrics`` — the serving loop owns the
        cadence). Scores SLOs and exports the goodput decomposition
        first so burn and ``Serve/goodput_*`` gauges ride the same
        flush."""
        from ..observability.metrics import publish_registry

        self.score_slo()
        if self.goodput is not None:
            self.goodput.export()
        return publish_registry(self.stats.registry, monitor, step,
                                default_step_counter="Serve/iterations")

    # ----------------------------------------------------------- telemetry
    def serve_telemetry(self, port: Optional[int] = None,
                        host: Optional[str] = None,
                        token: Optional[str] = None) -> int:
        """Start the live telemetry & control plane
        (:class:`~..observability.server.TelemetryServer`) for this
        engine; returns the bound port (pass ``port=0`` for an
        ephemeral one). Explicit arguments override the config block;
        idempotent — a second call returns the running server's port.

        The server thread only reads host-side state (registry under
        its own lock, scheduler tables copied per request) — it adds no
        device work, no syncs, and no compiled programs to the serving
        loop."""
        if self.telemetry is not None:
            return self.telemetry.port
        from ..observability.server import (TelemetryHooks, TelemetryServer,
                                            flight_summary)

        tcfg = self.cfg.telemetry
        host = host if host is not None else (
            tcfg.host if tcfg is not None else "127.0.0.1")
        port = port if port is not None else (
            tcfg.port if tcfg is not None else 0)
        token = token if token is not None else (
            tcfg.token if tcfg is not None else "")
        reg = self.stats.registry

        def refresh():
            # /metrics must carry the truth of NOW: the health mirror
            # (ready/draining/pool gauges) and the goodput decomposition
            # refresh before every exposition render
            self.health()
            if self.goodput is not None:
                self.goodput.export()

        hooks = TelemetryHooks(
            registry=reg,
            step_fn=lambda: int(reg.counter("Serve/iterations").value),
            refresh_fn=refresh,
            health_fn=self.health,
            requests_fn=self.requests_table,
            capacity_fn=lambda census: self.capacity_report(census=census),
            goodput_fn=(self.goodput.export if self.goodput is not None
                        else None),
            flight_fn=((lambda: flight_summary(self.flight))
                       if self.flight is not None else None),
            trace_fn=self._trace_endpoint,
            drain_fn=self._drain_control,
            dump_fn=((lambda: self.dump_flight("manual"))
                     if self.flight is not None else None),
            slo_reload_fn=self.reload_slo,
            scaling_fn=(self.scaling_snapshot
                        if self.loadscope is not None else None),
            tenants_fn=(self.tenants_snapshot
                        if self.tenantscope is not None else None))
        server = TelemetryServer(hooks, host=host, port=port, token=token)
        # bind FIRST: a failed bind (port in use) must not leave a dead
        # server object behind that makes the idempotency guard return
        # an unbound port on every retry
        bound = server.start()
        self.telemetry = server
        return bound

    def _trace_endpoint(self, rid: Optional[int]):
        """The ``GET /trace`` hook: ``?rid=N`` returns that request's
        hop-latency decomposition (:meth:`request_trace`); without a rid
        it returns the engine's span ring as a Chrome/Perfetto trace —
        None (→404) when spans are disabled or the rid is unknown."""
        if rid is not None:
            return self.request_trace(rid)
        if self.spans is None:
            return None
        from ..observability.export import to_chrome_trace

        return to_chrome_trace(self.spans.events(),
                               job_name=self.name or "serving")

    def _drain_control(self, end: bool) -> dict:
        """The ``POST /drain`` hook: begin (default) or end
        (``{"end": true}``) a graceful drain; returns the resulting
        health-relevant state."""
        if end:
            self.end_drain()
        else:
            self.begin_drain()
        return {"draining": self._draining,
                "queue_depth": self.sched.queue_depth,
                "occupancy": self.sched.occupancy}

    def close(self) -> None:
        """Teardown: stop the telemetry server's listener thread (when
        one is running). Safe to call more than once; the engine remains
        usable for serving afterwards."""
        if self.telemetry is not None:
            self.telemetry.close()
            self.telemetry = None
