"""Quantization-aware training (QAT) fake quantization with STE.

Analog of the reference's weight/activation quantization in
``compression/basic_layer.py`` (``LinearLayer_Compress`` weight-quantization
branch) and ``compression/utils.py``: quantize→dequantize in the forward so
the network learns under quantization noise, straight-through estimator in
the backward. Symmetric or asymmetric, per-tensor or per-group along the
last axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.custom_vjp
def ste_round(x):
    return jnp.round(x)


def _ste_fwd(x):
    return jnp.round(x), None


def _ste_bwd(_, g):
    return (g,)   # straight-through: d round(x)/dx := 1


ste_round.defvjp(_ste_fwd, _ste_bwd)


def fake_quant(w, bits: int = 8, *, group_size: int | None = None,
               symmetric: bool = True):
    """Quantize-dequantize ``w`` to ``bits`` (QAT forward). Scales are
    computed per group of ``group_size`` along the LAST axis (None =
    per-tensor-row granularity of that axis)."""
    orig_dtype = w.dtype
    x = w.astype(jnp.float32)
    shape = x.shape
    if group_size and shape[-1] % group_size == 0:
        x = x.reshape(shape[:-1] + (shape[-1] // group_size, group_size))
    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-10)
        q = jnp.clip(ste_round(x / scale), -qmax - 1, qmax)
        x = q * scale
    else:
        levels = 2.0 ** bits - 1
        lo = jnp.min(x, axis=-1, keepdims=True)
        hi = jnp.max(x, axis=-1, keepdims=True)
        scale = jnp.maximum((hi - lo) / levels, 1e-10)
        q = jnp.clip(ste_round((x - lo) / scale), 0, levels)
        x = q * scale + lo
    return x.reshape(shape).astype(orig_dtype)
