"""MoQ: mixed-precision quantization-aware training schedule.

Analog of the reference's MoQ (``quantize_training``): QAT starts wide
(``start_bits``) and steps the fake-quant bit width down toward the target,
either on a fixed step period or — the part that makes it MoQ — gated on
the measured loss curvature: the reference consults its eigenvalue module
before narrowing precision (``runtime/engine.py:2116-2127``,
``runtime/quantize.py`` schedule), the intuition being that narrowing is
safe once the loss landscape has flattened. Here the curvature probe is
``utils/eigenvalue.py``'s jittable power iteration, and a bit-width switch
is one retrace of the compiled step (the bit width rides the same static
``comp_active`` argument the compression techniques already use, encoded
as ``"weight_quantization:<bits>"``).
"""

from __future__ import annotations

from typing import Callable, Optional


class MoQScheduler:
    """Holds the current QAT bit width and decides when to narrow it."""

    def __init__(self, wq_cfg):
        self.target_bits = int(wq_cfg.bits)
        self.bits = int(wq_cfg.start_bits or wq_cfg.bits)
        if self.bits < self.target_bits:
            raise ValueError(
                f"MoQ start_bits ({self.bits}) must be >= target bits "
                f"({self.target_bits})")
        self.period = max(1, int(wq_cfg.quantize_period))
        self.use_eigenvalue = bool(wq_cfg.eigenvalue)
        self.threshold = float(wq_cfg.eigenvalue_threshold)
        self.initial_eig: Optional[float] = None
        self.history: list = []     # (step, eigenvalue, bits) probe ledger

    @property
    def active(self) -> bool:
        return self.bits > self.target_bits

    def maybe_step(self, step: int, eig_fn: Callable[[], float]) -> None:
        """Advance the schedule at ``step``. ``eig_fn`` is called only on
        probe steps (period boundaries) and only in eigenvalue mode — it
        returns the dominant Hessian eigenvalue of the current loss."""
        if not self.active or step == 0 or step % self.period != 0:
            return
        if self.use_eigenvalue:
            eig = abs(float(eig_fn()))
            self.history.append((step, eig, self.bits))
            if self.initial_eig is None:
                # first probe anchors the scale; never narrow on it
                self.initial_eig = max(eig, 1e-12)
                return
            if eig > self.threshold * self.initial_eig:
                return          # landscape still sharp: hold precision
        self.bits = max(self.target_bits, self.bits // 2)

    def annotate(self, comp_active: tuple) -> tuple:
        """Rewrite the weight_quantization entry to carry the scheduled
        bit width (static jit argument: a switch is one retrace)."""
        return tuple(f"weight_quantization:{self.bits}"
                     if n == "weight_quantization" else n
                     for n in comp_active)
