"""Layer reduction: initialize a shallower student from a teacher.

Analog of the reference's layer-reduction path in
``compression/compress.py:100`` (``init_compression`` with
``layer_reduction``): pick ``keep_layers`` of the teacher's L layers (e.g.
[0, 3, 7, 11] for 12→4 distillation init), remap the student's layer stack.
In the stacked (L, ...) layout this is one gather along dim 0 per leaf —
no module surgery."""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np


def reduce_layers(cfg, params: dict, keep_layers: Sequence[int]):
    """(teacher cfg, teacher params, kept indices) → (student cfg, params).

    Non-layer leaves (embeddings, final norm, head) carry over unchanged."""
    keep = list(keep_layers)
    L = cfg.n_layer
    if not keep or any(not 0 <= i < L for i in keep):
        raise ValueError(f"keep_layers {keep} out of range for n_layer={L}")
    idx = np.asarray(keep)
    student_cfg = dataclasses.replace(cfg, n_layer=len(keep))
    out = dict(params)
    out["layers"] = jax.tree.map(lambda a: a[idx], params["layers"])
    return student_cfg, out
