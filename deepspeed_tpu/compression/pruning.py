"""Pruning masks: unstructured (sparse), row (FFN channel), and head.

Analog of the reference ``compression/basic_layer.py`` pruning branches
(``sparse_pruning``, ``row_pruning``, ``head_pruning``): masks are computed
from current weight magnitudes each forward (dynamic sparse training) and
multiply the weights — gradients flow to surviving entries via the product
rule, matching the reference's mask-buffer semantics.

All weights here carry the stacked layer dim (L, ...) — statistics are per
layer (axis 0 excluded from reductions).
"""

from __future__ import annotations

import jax.numpy as jnp


def magnitude_mask(w, density: float):
    """Unstructured keep-top-|density| mask per layer. w: (L, ...)."""
    L = w.shape[0]
    flat = jnp.abs(w.reshape(L, -1)).astype(jnp.float32)
    thresh = jnp.quantile(flat, 1.0 - density, axis=1, keepdims=True)
    mask = (flat >= thresh).astype(w.dtype).reshape(w.shape)
    return mask


def row_masks(w_in, w_out, density: float):
    """FFN channel pruning: drop low-norm intermediate channels
    consistently — columns of w_in (L, d, f) and rows of w_out (L, f, d)."""
    norms = jnp.linalg.norm(w_in.astype(jnp.float32), axis=1)      # (L, f)
    thresh = jnp.quantile(norms, 1.0 - density, axis=1, keepdims=True)
    keep = (norms >= thresh)                                        # (L, f)
    return (keep[:, None, :].astype(w_in.dtype),                    # w_in cols
            keep[:, :, None].astype(w_out.dtype))                   # w_out rows


def head_mask(wo, n_head: int, density: float):
    """Attention head pruning: drop low-norm heads — row-groups of
    wo (L, h*hd, d). Keeps ceil(density * n_head) heads per layer."""
    L, hhd, d = wo.shape
    hd = hhd // n_head
    per_head = jnp.linalg.norm(
        wo.astype(jnp.float32).reshape(L, n_head, hd * d), axis=-1)  # (L, h)
    n_keep = max(1, int(round(density * n_head)))
    kth = jnp.sort(per_head, axis=1)[:, n_head - n_keep][:, None]
    keep = (per_head >= kth).astype(wo.dtype)                        # (L, h)
    return jnp.repeat(keep, hd, axis=1)[:, :, None]                  # (L,h*hd,1)
