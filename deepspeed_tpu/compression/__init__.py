from .compress import (apply_compression, clean_params, convert_to_compressed,
                       init_compression)
from .layer_reduction import reduce_layers
from .pruning import head_mask, magnitude_mask, row_masks
from .quantization import fake_quant

__all__ = ["fake_quant", "magnitude_mask", "row_masks", "head_mask",
           "reduce_layers", "init_compression", "convert_to_compressed",
           "apply_compression", "clean_params"]
