"""Compression entry points: config-driven QAT + pruning over the trunk.

Analog of the reference ``compression/compress.py`` (``init_compression``
``:100`` / ``redundancy_clean`` ``:148``) and its scheduler: where the
reference swaps ``nn.Linear`` for ``LinearLayer_Compress`` modules matched by
name patterns, the TPU-native version is a **pure function over the param
pytree** applied inside the loss — the engine's compiled step quantizes/masks
the compute weights each forward, the optimizer still updates full-precision
masters, and gradients flow through STE/mask products.

Technique activation follows the config ``schedule_offset`` (reference
scheduler semantics); the engine passes the active-technique set as a static
jit argument, so crossing an offset is one retrace.
"""

from __future__ import annotations

from typing import Iterable

import jax

from .pruning import head_mask, magnitude_mask, row_masks
from .quantization import fake_quant

# leaves eligible for weight quantization / sparse pruning (matmul weights,
# the reference's Linear targets)
_QUANT_LEAVES = ("wq", "wk", "wv", "wo", "w_in", "w_out", "w_gate")


def apply_compression(params: dict, cfg, active: Iterable[str], *,
                      n_head: int) -> dict:
    """Return params with the ``active`` techniques applied to the layer
    stack. ``cfg`` is the CompressionConfig node; ``active`` ⊆
    {'weight_quantization', 'sparse_pruning', 'row_pruning', 'head_pruning'}."""
    active = set(active)
    if not active:
        return params
    # MoQ-annotated entries carry the scheduled bit width
    # ("weight_quantization:<bits>", compression/moq.py)
    wq_bits = None
    for entry in list(active):
        if entry.startswith("weight_quantization:"):
            active.discard(entry)
            active.add("weight_quantization")
            wq_bits = int(entry.split(":", 1)[1])
    layers = dict(params["layers"])

    if "weight_quantization" in active:
        wq = cfg.weight_quantization
        for name in _QUANT_LEAVES:
            if name in layers:
                layers[name] = fake_quant(layers[name],
                                          wq_bits or wq.bits,
                                          group_size=wq.group_size or None,
                                          symmetric=wq.symmetric)
    if "sparse_pruning" in active:
        for name in _QUANT_LEAVES:
            if name in layers:
                layers[name] = layers[name] * magnitude_mask(
                    layers[name], cfg.sparse_pruning.density)
    if "row_pruning" in active and "w_in" in layers and "w_out" in layers:
        m_in, m_out = row_masks(layers["w_in"], layers["w_out"],
                                cfg.row_pruning.density)
        layers["w_in"] = layers["w_in"] * m_in
        layers["w_out"] = layers["w_out"] * m_out
        if "b_in" in layers:
            layers["b_in"] = layers["b_in"] * m_in[:, 0, :]
    if "head_pruning" in active and "wo" in layers:
        layers["wo"] = layers["wo"] * head_mask(layers["wo"], n_head,
                                                cfg.head_pruning.density)
    return {**params, "layers": layers}


class CompressionMixin:
    """Model wrapper: compresses compute params inside loss/apply.

    ``comp_active`` is set by the engine per trace (static argument), like
    random-LTD's kept-token count."""

    comp_cfg = None
    comp_active: tuple = ()

    def set_compression_active(self, names) -> None:
        self.comp_active = tuple(names)

    def _compress(self, params):
        if self.comp_cfg is None or not self.comp_active:
            return params
        return apply_compression(params, self.comp_cfg, self.comp_active,
                                 n_head=self.cfg.n_head)

    def loss(self, params, batch, **kw):
        return super().loss(self._compress(params), batch, **kw)

    def apply(self, params, input_ids, **kw):
        return super().apply(self._compress(params), input_ids, **kw)


def convert_to_compressed(model, compression_cfg):
    """Wrap a built model with config-driven compression (reference
    ``init_compression``). Same params/specs; loss/apply compress first."""
    cls = type(model)
    new_cls = type(f"Compressed{cls.__name__}", (CompressionMixin, cls), {})
    new = object.__new__(new_cls)
    new.__dict__.update(model.__dict__)
    new.comp_cfg = compression_cfg
    new.comp_active = ()
    return new


# keep the reference's entry-point name
init_compression = convert_to_compressed


def clean_params(params: dict, cfg, *, n_head: int) -> dict:
    """Bake all enabled techniques into the weights for export (reference
    ``redundancy_clean``): the returned params ARE the compressed network."""
    active = [name for name in ("weight_quantization", "sparse_pruning",
                                "row_pruning", "head_pruning")
              if getattr(cfg, name).enabled]
    out = apply_compression(params, cfg, active, n_head=n_head)
    return jax.tree.map(lambda a: a, out)   # materialize fresh leaves
