"""JAX version compatibility shims.

The codebase targets the jax==0.9 API surface (``jax.shard_map``,
``jax.set_mesh``, ``jax.sharding.get_abstract_mesh``); some deployment
images still carry a 0.4.x JAX where those names live under
``jax.experimental`` or do not exist. Importing this module (the first
import in ``deepspeed_tpu/__init__.py``) installs forward-compatible
aliases on the ``jax`` module so the rest of the package — and user code
written against the pinned API — runs unchanged on both.

Kept dependency-free (imports only jax) so ``import deepspeed_tpu.compat``
can never cycle back into the package.
"""

from __future__ import annotations

import contextlib

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        # 0.9 renamed check_rep -> check_vma; translate and delegate.
        # Default OFF when unspecified: 0.4's replication checker lacks
        # rules for primitives the 0.9 checker handles (checkpoint_name's
        # `name`, sharding_constraint), and bodies written against 0.9
        # trip it spuriously.
        kw.setdefault("check_rep",
                      check_vma if check_vma is not None else False)
        # 0.9's axis_names (the manual subset) is 0.4's complement of
        # `auto` (the non-manual subset).
        axis_names = kw.pop("axis_names", None)
        if axis_names is not None:
            kw.setdefault("auto",
                          frozenset(mesh.axis_names) - frozenset(axis_names))
        return _shard_map_04(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)

    jax.shard_map = _shard_map

if not hasattr(jax.lax, "pcast"):
    # 0.9's replication-cast for manual regions. 0.4 shard_map bodies
    # with check_rep=False track no replication types — identity is the
    # faithful translation.
    jax.lax.pcast = lambda x, axis_name=None, **kw: x

if not hasattr(jax.lax, "axis_size"):
    # 0.9's lax.axis_size; psum of a literal 1 constant-folds to the
    # bound axis size on 0.4.
    jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)

if not hasattr(jax, "set_mesh"):
    # 0.9's ``with jax.set_mesh(mesh):`` — on 0.4 a Mesh is already a
    # context manager that installs itself as the thread-resources env
    # (which is exactly what ``current_mesh()``'s legacy branch reads).
    @contextlib.contextmanager
    def _set_mesh(mesh):
        with mesh:
            yield mesh

    jax.set_mesh = _set_mesh
