"""Experiment monitoring fan-out.

Analog of ``deepspeed/monitor/monitor.py:29`` (``MonitorMaster``): rank-0
event writer dispatching to TensorBoard / CSV / WandB backends, driven by the
``monitor`` config block. Events are ``(name, value, step)`` tuples.
"""

from __future__ import annotations

import csv
import os
from pathlib import Path
from typing import Sequence

import jax

from ..utils.logging import logger


class _CsvWriter:
    def __init__(self, cfg: dict):
        self.dir = Path(cfg.get("output_path", "./csv_monitor"))
        self.job = cfg.get("job_name", "DeepSpeedTpuJob")
        self.dir.mkdir(parents=True, exist_ok=True)
        self._files: dict[str, object] = {}

    def write_events(self, events: Sequence[tuple]):
        for name, value, step in events:
            fname = self.dir / (name.replace("/", "_") + ".csv")
            new = not fname.exists()
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, float(value)])


class _TensorboardWriter:
    def __init__(self, cfg: dict):
        from torch.utils.tensorboard import SummaryWriter  # torch-cpu is baked in

        out = os.path.join(cfg.get("output_path", "./runs"), cfg.get("job_name", "job"))
        self.writer = SummaryWriter(log_dir=out)

    def write_events(self, events: Sequence[tuple]):
        for name, value, step in events:
            self.writer.add_scalar(name, float(value), int(step))
        self.writer.flush()


class _WandbWriter:
    def __init__(self, cfg: dict):
        import wandb

        wandb.init(project=cfg.get("project", "deepspeed_tpu"),
                   group=cfg.get("group"), team=cfg.get("team"))
        self.wandb = wandb

    def write_events(self, events: Sequence[tuple]):
        for name, value, step in events:
            self.wandb.log({name: float(value)}, step=int(step))


class MonitorMaster:
    def __init__(self, cfg):
        self.writers = []
        if jax.process_index() != 0:
            return
        if cfg.tensorboard.get("enabled"):
            try:
                self.writers.append(_TensorboardWriter(cfg.tensorboard))
            except Exception as e:  # tensorboard optional
                logger.warning(f"tensorboard monitor disabled: {e}")
        if cfg.csv_monitor.get("enabled"):
            self.writers.append(_CsvWriter(cfg.csv_monitor))
        if cfg.wandb.get("enabled"):
            try:
                self.writers.append(_WandbWriter(cfg.wandb))
            except Exception as e:
                logger.warning(f"wandb monitor disabled: {e}")

    @property
    def enabled(self) -> bool:
        return bool(self.writers)

    def write_events(self, events: Sequence[tuple]):
        for w in self.writers:
            w.write_events(events)
