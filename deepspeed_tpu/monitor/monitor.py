"""Experiment monitoring fan-out.

Analog of ``deepspeed/monitor/monitor.py:29`` (``MonitorMaster``): rank-0
event writer dispatching to TensorBoard / CSV / WandB backends — plus the
machine-readable sinks from ``observability/sinks.py`` (JSONL event log,
Prometheus textfile) — driven by the ``monitor`` config block. Events are
``(name, value, step)`` tuples.

Writers keep their file handles open for the life of the master (the old
CSV writer re-opened its file per event — measurable syscall overhead at
per-step cadence); the engines call ``flush()`` at report boundaries and
``close()`` on teardown.
"""

from __future__ import annotations

import csv
import os
from pathlib import Path
from typing import Sequence

import jax

from ..utils.logging import logger


class _CsvWriter:
    """One append-mode CSV per metric name, handles kept open."""

    def __init__(self, cfg: dict):
        self.dir = Path(cfg.get("output_path", "./csv_monitor"))
        self.job = cfg.get("job_name", "DeepSpeedTpuJob")
        self.dir.mkdir(parents=True, exist_ok=True)
        self._files: dict[str, object] = {}      # name -> open file
        self._writers: dict[str, csv.writer] = {}

    def _writer(self, name: str):
        w = self._writers.get(name)
        if w is None:
            fname = self.dir / (name.replace("/", "_") + ".csv")
            new = not fname.exists() or fname.stat().st_size == 0
            f = open(fname, "a", newline="")
            w = csv.writer(f)
            if new:
                w.writerow(["step", name])
            self._files[name] = f
            self._writers[name] = w
        return w

    def write_events(self, events: Sequence[tuple]):
        for name, value, step in events:
            self._writer(name).writerow([step, float(value)])

    def flush(self):
        for f in self._files.values():
            if not f.closed:
                f.flush()

    def close(self):
        for f in self._files.values():
            if not f.closed:
                f.close()
        self._files.clear()
        self._writers.clear()


class _TensorboardWriter:
    def __init__(self, cfg: dict):
        from torch.utils.tensorboard import SummaryWriter  # torch-cpu is baked in

        out = os.path.join(cfg.get("output_path", "./runs"), cfg.get("job_name", "job"))
        self.writer = SummaryWriter(log_dir=out)

    def write_events(self, events: Sequence[tuple]):
        for name, value, step in events:
            self.writer.add_scalar(name, float(value), int(step))
        self.writer.flush()

    def flush(self):
        self.writer.flush()

    def close(self):
        self.writer.close()


class _WandbWriter:
    def __init__(self, cfg: dict):
        import wandb

        wandb.init(project=cfg.get("project", "deepspeed_tpu"),
                   group=cfg.get("group"), team=cfg.get("team"))
        self.wandb = wandb

    def write_events(self, events: Sequence[tuple]):
        for name, value, step in events:
            self.wandb.log({name: float(value)}, step=int(step))


class MonitorMaster:
    def __init__(self, cfg):
        self.writers = []
        if jax.process_index() != 0:
            return
        if cfg.tensorboard.get("enabled"):
            try:
                self.writers.append(_TensorboardWriter(cfg.tensorboard))
            except Exception as e:  # tensorboard optional
                logger.warning(f"tensorboard monitor disabled: {e}")
        if cfg.csv_monitor.get("enabled"):
            self.writers.append(_CsvWriter(cfg.csv_monitor))
        if cfg.wandb.get("enabled"):
            try:
                self.writers.append(_WandbWriter(cfg.wandb))
            except Exception as e:
                logger.warning(f"wandb monitor disabled: {e}")
        if getattr(cfg, "jsonl", {}).get("enabled"):
            from ..observability.sinks import JsonlSink

            self.writers.append(JsonlSink(cfg.jsonl))
        if getattr(cfg, "prometheus", {}).get("enabled"):
            from ..observability.sinks import PrometheusTextfileSink

            self.writers.append(PrometheusTextfileSink(cfg.prometheus))
        if getattr(cfg, "request_log", {}).get("enabled"):
            from ..observability.export import RequestLogSink

            # per-request records, not scalar events: serving engines find
            # this writer via ServingEngine.attach_monitor(monitor)
            self.writers.append(RequestLogSink(cfg.request_log))

    @property
    def enabled(self) -> bool:
        return bool(self.writers)

    def write_events(self, events: Sequence[tuple]):
        for w in self.writers:
            w.write_events(events)

    def flush(self):
        """Push buffered events to disk (engines call this at report
        boundaries; sinks without buffering just no-op)."""
        for w in self.writers:
            fl = getattr(w, "flush", None)
            if fl is not None:
                fl()

    def close(self):
        for w in self.writers:
            cl = getattr(w, "close", None)
            if cl is not None:
                cl()
        self.writers = []
