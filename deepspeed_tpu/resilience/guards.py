"""Typed failure taxonomy: statuses and errors callers can branch on.

The pre-resilience code signalled failures with bare ``RuntimeError``s and
left callers inferring request outcomes from token shapes. Every guard in
this layer instead lands in exactly one of these types, so backpressure,
retry, and triage logic never string-matches a message.
"""

from __future__ import annotations

import enum


class RequestStatus(enum.Enum):
    """Terminal outcome of a served request (``Request.status``).

    ``OK``        — finished normally (eos or max_new reached);
    ``NONFINITE`` — the per-row logit guard saw NaN/Inf in this request's
                    logits and retired it (other slots are untouched —
                    the parity test pins bit-identity);
    ``TIMEOUT``   — a TTFT or total-wall deadline expired;
    ``CANCELLED`` — ``cancel(rid)`` retired it;
    ``SHED``      — rejected at admission (queue full / draining) — the
                    status carried by :class:`QueueFullError`;
    ``REQUEUED``  — NOT terminal: the fleet router moved this request to
                    a surviving replica after its original replica was
                    lost (``Request.attempts`` counts the moves). The
                    request is live again and finishes with one of the
                    terminal statuses above — the transition exists as a
                    status so the in-flight table and the request log
                    show failover per request instead of hiding it.
    """

    OK = "ok"
    NONFINITE = "nonfinite"
    TIMEOUT = "timeout"
    CANCELLED = "cancelled"
    SHED = "shed"
    REQUEUED = "requeued"


class QueueFullError(RuntimeError):
    """``submit()`` rejected a request: queue at capacity or the engine is
    draining. Subclasses ``RuntimeError`` so pre-resilience callers that
    caught the old bare error keep working; new callers catch THIS type
    and backpressure on ``.status`` / ``.queue_depth`` instead of parsing
    the message."""

    status = RequestStatus.SHED

    def __init__(self, message: str, queue_depth: int | None = None,
                 max_queue: int | None = None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.max_queue = max_queue


class PagePoolExhausted(QueueFullError):
    """``submit()`` rejected a request the paged KV pool can never hold:
    its worst-case page count (``ceil((prompt + max_new - 1) /
    page_size)``, assuming zero prefix sharing — shared pages can be
    evicted out from under a queued request, so admission must not bet
    on them) exceeds the pool's usable pages. Status ``SHED``, like every
    admission refusal; a TRANSIENTLY full pool never raises — the
    request waits at the queue head and admits after a retirement frees
    pages. Subclasses :class:`QueueFullError` so existing backpressure
    handlers shed it the same way."""

    def __init__(self, message: str, pages_needed: int | None = None,
                 pages_usable: int | None = None):
        super().__init__(message)
        self.pages_needed = pages_needed
        self.pages_usable = pages_usable


class NonFiniteLossError(RuntimeError):
    """The training-side sentinel: raised after K consecutive bad optimizer
    steps (fp16 overflow skips, or non-finite loss at a report boundary)
    so a collapsed run halts instead of burning the remaining budget.
    Carries the streak and the last loss for the post-mortem."""

    def __init__(self, message: str, streak: int = 0,
                 last_loss: float | None = None):
        super().__init__(message)
        self.streak = streak
        self.last_loss = last_loss


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint tag failed manifest verification (missing commit
    marker, size mismatch, checksum mismatch) and no fallback was
    possible — or the caller pinned an explicit tag, where silent
    fallback would be worse than failing."""

    def __init__(self, message: str, tag: str | None = None,
                 reason: str | None = None):
        super().__init__(message)
        self.tag = tag
        self.reason = reason
