"""Checkpoint integrity: manifests, verification, fallback, pruning.

The commit protocol (runtime/checkpoint/engine.py) writes, in order:

1. the orbax/tensorstore state (collective, possibly async);
2. ``meta.json`` (config + step metadata, rank 0);
3. ``manifest.json`` — per-file sizes (+ sha256 at ``verify: "checksum"``)
   over everything under ``<tag>/state``, written LAST via atomic rename:
   its presence IS the commit marker (the reference's Nebula service and
   torch-elastic use the same marker-written-last discipline);
4. the ``latest`` pointer flip.

A crash between (1) and (3) leaves a tag with no manifest: storage is
consumed but nothing ever points at it, and load-time verification skips
it. A crash between (3) and (4) leaves a fully verified tag that
``latest`` doesn't name — ``newest_verified_tag`` still finds it for
``resume="auto"``. ``latest`` therefore never names a torn checkpoint.

Verification levels (``config.checkpoint.verify``):
- ``"off"``      — trust the directory (pre-resilience behavior);
- ``"size"``     — manifest present + every file exists at its recorded
                   size (catches torn/partial writes; default);
- ``"checksum"`` — additionally sha256 every file (catches bit rot; costs
                   a full read-back of the checkpoint at save AND load).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from pathlib import Path
from typing import Optional

from ..utils.logging import log_dist, warning_once

MANIFEST = "manifest.json"
_CHUNK = 1 << 20


def _sha256(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(_CHUNK), b""):
            h.update(chunk)
    return h.hexdigest()


def _state_files(tag_dir: Path) -> list[Path]:
    state = tag_dir / "state"
    return sorted(p for p in state.rglob("*") if p.is_file())


def write_manifest(tag_dir: Path | str, level: str = "size",
                   extra: Optional[dict] = None) -> Optional[dict]:
    """Write ``<tag>/manifest.json`` over the already-durable state files.

    Must be called only AFTER the state write has committed (the async
    path calls it from ``wait_for_checkpoint``, after
    ``wait_until_finished``). Atomic: written to a temp name and
    ``os.replace``d, so a reader never sees a half manifest. Returns the
    manifest dict, or None at ``level="off"`` (no marker written — the
    tag stays legacy-shaped on purpose)."""
    if level == "off":
        return None
    tag_dir = Path(tag_dir)
    files = {}
    for p in _state_files(tag_dir):
        rel = p.relative_to(tag_dir).as_posix()
        entry: dict = {"bytes": p.stat().st_size}
        if level == "checksum":
            entry["sha256"] = _sha256(p)
        files[rel] = entry
    manifest = {"version": 1, "tag": tag_dir.name, "level": level,
                "files": files, **(extra or {})}
    tmp = tag_dir / (MANIFEST + ".tmp")
    tmp.write_text(json.dumps(manifest, indent=2))
    os.replace(tmp, tag_dir / MANIFEST)
    return manifest


def verify_tag(tag_dir: Path | str, level: str = "size") -> tuple[str, str]:
    """Verify one tag directory against its manifest.

    Returns ``(status, reason)`` with status one of:
    - ``"verified"`` — manifest present and every check at ``level`` passed;
    - ``"legacy"``   — no manifest (pre-resilience checkpoint, or
                       ``verify: "off"`` at save time). Callers accept it
                       with a one-shot warning — refusing every checkpoint
                       written before this layer existed would be worse;
    - ``"corrupt"``  — the manifest disagrees with the bytes on disk
                       (``reason`` names the first mismatch).
    """
    tag_dir = Path(tag_dir)
    if level == "off":
        return "verified", "verification off"
    mf = tag_dir / MANIFEST
    if not mf.exists():
        return "legacy", "no manifest (pre-resilience checkpoint?)"
    try:
        manifest = json.loads(mf.read_text())
    except (OSError, ValueError) as e:
        return "corrupt", f"unreadable manifest: {e}"
    for rel, entry in manifest.get("files", {}).items():
        p = tag_dir / rel
        if not p.exists():
            return "corrupt", f"missing file {rel}"
        size = p.stat().st_size
        if size != entry["bytes"]:
            return "corrupt", (f"size mismatch {rel}: manifest "
                               f"{entry['bytes']} vs disk {size}")
        if level == "checksum":
            want = entry.get("sha256")
            if want is None:
                warning_once(
                    f"checkpoint verify=checksum but the manifest in "
                    f"{tag_dir} was written size-only — verifying sizes "
                    "for this tag (re-save to get checksums)")
            elif _sha256(p) != want:
                return "corrupt", f"checksum mismatch {rel}"
    return "verified", ""


def _tag_step(tag_dir: Path) -> int:
    """Ordering key for fallback/prune: the step recorded in meta.json
    (mtime as the tiebreak-ish fallback for tags saved without one)."""
    meta = tag_dir / "meta.json"
    if meta.exists():
        try:
            return int(json.loads(meta.read_text()).get("global_steps", -1))
        except (OSError, ValueError):
            pass
    return -1


def list_tags(base: Path | str) -> list[Path]:
    """Tag directories under ``base``, oldest → newest (by recorded step,
    then mtime)."""
    base = Path(base)
    if not base.is_dir():
        return []
    tags = [d for d in base.iterdir() if d.is_dir() and (d / "state").exists()]
    return sorted(tags, key=lambda d: (_tag_step(d), d.stat().st_mtime))


def newest_verified_tag(base: Path | str, level: str = "size",
                        exclude: Optional[set] = None,
                        accept_legacy: bool = False) -> Optional[str]:
    """Newest tag under ``base`` that passes verification, or None.
    ``exclude`` skips tags already known bad (e.g. the one ``latest``
    named).

    ``accept_legacy=False`` (the default) also skips manifest-less tags:
    in a FALLBACK scan a tag without its commit marker is far more likely
    a save that died mid-state-write than a pre-resilience archive —
    selecting it would hand orbax torn bytes and an untyped crash, the
    exact failure this module exists to prevent. (A legacy tag that the
    ``latest`` pointer explicitly names still loads, with a warning —
    the pointer is commit evidence the scan doesn't have.)"""
    exclude = exclude or set()
    for d in reversed(list_tags(base)):
        if d.name in exclude:
            continue
        status, reason = verify_tag(d, level)
        if status == "verified" or (status == "legacy" and accept_legacy):
            return d.name
        log_dist(f"checkpoint fallback: skipping {status} tag {d.name!r} "
                 f"({reason})", ranks=[0], level="WARNING")
    return None


def prune_tags(base: Path | str, keep_last: int,
               protect: Optional[set] = None) -> list[str]:
    """Delete the oldest tags beyond the newest ``keep_last``; never the
    ``protect``ed ones (the tag just written, and whatever ``latest``
    names). 0 disables. Returns the deleted tag names. Process-0 only —
    the caller gates on rank."""
    if keep_last <= 0:
        return []
    protect = protect or set()
    tags = list_tags(base)
    doomed = [d for d in tags[:-keep_last] if d.name not in protect] \
        if len(tags) > keep_last else []
    deleted = []
    for d in doomed:
        shutil.rmtree(d, ignore_errors=True)
        deleted.append(d.name)
    if deleted:
        log_dist(f"checkpoint: pruned {len(deleted)} old tag(s) "
                 f"(keep_last={keep_last}): {deleted}", ranks=[0])
    return deleted
