"""Deterministic fault injection: the chaos half of every resilience test.

Two delivery mechanisms, both inert-by-default:

- **Config-gated** (:class:`ChaosConfig` → :class:`ChaosMonkey`): serving
  faults that must be seeded and repeatable — poison ONE occupied slot's
  logits with NaN on decode step N, sleep through an iteration to trip
  the decode-step watchdog, flood the queue at startup. The serving
  engine only constructs a monkey when ``chaos.enabled`` is true; with
  chaos off the engine holds ``None`` and the hot path pays a single
  ``is not None`` check — no extra host syncs, no extra programs
  (the acceptance gate: ``bench_serving.py --smoke``'s compile freeze
  still passes).

- **Environment-gated** (:func:`kill_point` / :func:`preempt_step`):
  process-death faults that only make sense in a subprocess test — die
  with ``os._exit`` between the checkpoint state write and the ``latest``
  pointer flip, or raise SIGTERM at train step N to simulate a scheduler
  preemption. Library call sites are one dict lookup when the env var is
  unset.

Injection points are *named*; every firing is recorded (``injected`` audit
log for the monkey, an unbuffered stderr line for the kill points) so a
test asserts both the guard's reaction AND that the fault actually fired.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

import numpy as np

# Environment variables driving the process-death injection points.
# DSTPU_CHAOS_KILL="<point>" or "<point>:<k>" — os._exit(137) at the k-th
# (0-based, default 0) hit of that named kill point.
KILL_ENV = "DSTPU_CHAOS_KILL"
# DSTPU_CHAOS_PREEMPT="<step>" — SIGTERM this process at train step <step>.
PREEMPT_ENV = "DSTPU_CHAOS_PREEMPT"

# Named kill points wired into the checkpoint commit sequence
# (runtime/checkpoint/engine.py). The crash-mid-commit test kills at
# AFTER_STATE: the tag's arrays are durable but its manifest (the commit
# marker) and the 'latest' flip never happen — load must resume from the
# previous verified tag.
KILL_AFTER_STATE_WRITE = "ckpt:after-state-write"
KILL_BEFORE_LATEST_FLIP = "ckpt:before-latest-flip"

_kill_hits: dict[str, int] = {}


def kill_point(name: str) -> None:
    """Die HERE (``os._exit(137)`` — no atexit, no finally, the shape of a
    SIGKILL/OOM death) if ``DSTPU_CHAOS_KILL`` names this point.

    Format: ``"point"`` (die on first hit) or ``"point:k"`` (die on the
    k-th hit, 0-based) — so a test can let save #1 commit cleanly and
    kill save #2 mid-commit. Inert when the env var is unset (one dict
    lookup)."""
    spec = os.environ.get(KILL_ENV)
    if not spec:
        return
    # point names themselves contain ':' — the occurrence index is only
    # the LAST segment, and only when it's numeric
    point, sep, k = spec.rpartition(":")
    if not sep or not k.isdigit():
        point, k = spec, ""
    if point != name:
        return
    hit = _kill_hits.get(name, 0)
    _kill_hits[name] = hit + 1
    if hit != (int(k) if k else 0):
        return
    # unbuffered: the dying process must leave evidence the fault fired
    sys.stderr.write(f"[chaos] kill_point {name!r} hit {hit}: os._exit(137)\n")
    sys.stderr.flush()
    os._exit(137)


def preempt_step():
    """The train step at which chaos delivers SIGTERM to this process
    (simulated scheduler preemption), or None. Parsed per call but the
    engine caches the result once at init — the per-step cost with chaos
    off is a host ``is not None``."""
    spec = os.environ.get(PREEMPT_ENV)
    if not spec:
        return None
    return int(spec)


def deliver_preemption() -> None:
    """Raise SIGTERM in this process — the PreemptionGuard (or the default
    handler) takes it from here, exactly as under a real scheduler."""
    import signal

    sys.stderr.write("[chaos] delivering simulated SIGTERM preemption\n")
    sys.stderr.flush()
    os.kill(os.getpid(), signal.SIGTERM)


@dataclasses.dataclass
class ChaosConfig:
    """Seeded serving-fault schedule (``serving.chaos`` in ServingConfig).

    All injection points are deterministic: same config + same workload →
    same fault at the same step against the same slot. ``enabled: false``
    (the default) makes the whole config inert — the engine builds no
    monkey and the serving step is byte-for-byte the production program.
    """

    enabled: bool = False
    seed: int = 0
    # Poison ONE occupied slot's logits with NaN on the Nth serving decode
    # step (0-based; -1 = never). The slot is a seeded choice among the
    # occupied slots at that step. Proves the per-row non-finite guard:
    # exactly that request retires NONFINITE, every other slot's output
    # stays bit-identical to the no-fault run.
    nonfinite_decode_step: int = -1
    # Sleep ``hang_seconds`` inside the Nth serving iteration's decode
    # window (-1 = never): a hung/slow device step, as the watchdog sees it.
    hang_iteration: int = -1
    hang_seconds: float = 0.0
    # Submit this many junk one-token requests before the first iteration:
    # a queue flood. With ``max_queue`` set, the overflow sheds through
    # QueueFullError and the Serve/shed counter proves the backpressure path.
    flood_submits: int = 0

    def __post_init__(self):
        if self.hang_seconds < 0:
            raise ValueError(f"hang_seconds must be >= 0, "
                             f"got {self.hang_seconds}")
        if self.flood_submits < 0:
            raise ValueError(f"flood_submits must be >= 0, "
                             f"got {self.flood_submits}")

    @classmethod
    def from_any(cls, cfg: "ChaosConfig | dict | None") -> "ChaosConfig | None":
        if cfg is None or isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown chaos config keys: {sorted(unknown)}")
        return cls(**cfg)


@dataclasses.dataclass
class FleetChaosConfig:
    """Seeded fleet-level fault schedule (``FleetEngine(chaos=...)``).

    One fault class for now: replica loss. At fleet iteration
    ``kill_replica_step`` the fleet abruptly drops one live replica —
    its queued and in-flight requests requeue onto survivors with a
    typed ``REQUEUED`` transition and a bumped ``attempts`` counter (the
    zero-request-loss oracle in ``bench_fleet.py --smoke``). The victim
    is ``kill_replica`` when named, else a seeded choice among the live
    replicas at that instant. ``enabled: false`` (default) builds no
    monkey — the fleet step pays one ``is not None`` check."""

    enabled: bool = False
    seed: int = 0
    kill_replica_step: int = -1     # fleet iteration of the kill (-1 never)
    kill_replica: str = ""          # victim name; "" = seeded choice

    @classmethod
    def from_any(cls, cfg: "FleetChaosConfig | dict | None") \
            -> "FleetChaosConfig | None":
        if cfg is None or isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown fleet chaos config keys: {sorted(unknown)}")
        return cls(**cfg)


class FleetChaosMonkey:
    """Drives one :class:`FleetChaosConfig` against one FleetEngine:
    counts fleet iterations, picks the victim, keeps the ``injected``
    audit log tests assert against (the fault must actually fire)."""

    def __init__(self, cfg: FleetChaosConfig):
        self.cfg = cfg
        self.rng = np.random.default_rng(cfg.seed)
        self.injected: list[dict] = []
        self._iterations = 0

    def maybe_kill(self, live: list) -> "str | None":
        """Name of the replica to kill THIS fleet iteration, or None.
        ``live`` is the current replica-name list; a configured victim
        that already left the fleet degrades to a seeded choice."""
        it = self._iterations
        self._iterations += 1
        c = self.cfg
        if c.kill_replica_step < 0 or it != c.kill_replica_step or not live:
            return None
        victim = c.kill_replica if c.kill_replica in live \
            else str(self.rng.choice(sorted(live)))
        self.injected.append({"point": "replica_kill", "iteration": it,
                              "replica": victim})
        return victim


class ChaosMonkey:
    """Drives one :class:`ChaosConfig` against one ServingEngine.

    Owns its own iteration/decode-step counters (the engine just reports
    events), a seeded RNG for slot choice, and the ``injected`` audit log
    tests assert against. ``sleep`` is injectable for fake-time tests.
    """

    def __init__(self, cfg: ChaosConfig, sleep=time.sleep):
        self.cfg = cfg
        self.sleep = sleep
        self.rng = np.random.default_rng(cfg.seed)
        self.injected: list[dict] = []
        self._decode_steps = 0
        self._iterations = 0

    def on_iteration(self) -> int:
        """Count one serving iteration; returns its 0-based index."""
        it = self._iterations
        self._iterations += 1
        return it

    def maybe_hang(self, iteration: int) -> None:
        """Inside the decode timing window: simulate a hung step."""
        c = self.cfg
        if c.hang_iteration >= 0 and iteration == c.hang_iteration \
                and c.hang_seconds > 0:
            self.injected.append({"point": "hang", "iteration": iteration,
                                  "seconds": c.hang_seconds})
            self.sleep(c.hang_seconds)

    def poison_slot(self, occupied) -> int:
        """Slot whose logits this decode step poisons, or -1.

        Counts decode steps internally; fires once, on
        ``nonfinite_decode_step``, against a seeded choice among the
        occupied slots (never an empty batch — an unoccupied row has no
        request to retire)."""
        i = self._decode_steps
        self._decode_steps += 1
        c = self.cfg
        if c.nonfinite_decode_step >= 0 and i == c.nonfinite_decode_step \
                and len(occupied):
            slot = int(self.rng.choice(sorted(occupied)))
            self.injected.append({"point": "nonfinite", "decode_step": i,
                                  "slot": slot})
            return slot
        return -1
