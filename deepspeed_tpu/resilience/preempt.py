"""Preemption guard: SIGTERM → durable checkpoint → clean exit.

Schedulers (k8s, GCE spot/preemptible, slurm) deliver SIGTERM with a
grace window before the SIGKILL. Without a handler, a Python default
death mid-async-save leaves the newest checkpoint uncommitted and
``latest`` pointing one save back — a whole save interval of work lost.
The guard turns the signal into: await the in-flight async commit, write
the manifest, flip ``latest``, then exit — so the *newest* checkpoint is
the one the next incarnation resumes from.

The reference stack gets the same property from torch-elastic's
SIGTERM-aware agent + Nebula's persistence service; here it is one
handler installed next to the training loop:

    engine = ds.initialize(cfg, model)
    guard = PreemptionGuard(engine).install()
    for batch in loader:
        engine.train_batch(batch)
        if step % save_every == 0:
            engine.save_checkpoint(ckpt_dir)
        if guard.preempted:          # cooperative path, if you prefer
            break                    # to exit the loop yourself
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

from ..utils.logging import log_dist

# 128 + SIGTERM(15): the conventional "died to SIGTERM" exit code, which
# supervisors (incl. elasticity/agent.py) read as a restartable death.
DEFAULT_EXIT_CODE = 143


class PreemptionGuard:
    """SIGTERM handler that makes the in-flight checkpoint durable first.

    ``exit_on_signal=True`` (default) raises ``SystemExit(exit_code)``
    from the handler once the commit is durable — the process unwinds
    through ``finally`` blocks and atexit (unlike a default-action
    SIGTERM death). ``exit_on_signal=False`` only sets ``preempted`` for
    a cooperative loop that wants to break on its own schedule; the
    commit is still awaited inside the handler, so even a loop that
    never checks the flag exits with a loadable checkpoint.

    ``save_dir`` + ``save_on_preempt=True`` additionally snapshots the
    CURRENT state before exiting (for long save intervals where the last
    committed checkpoint may be many steps old). The extra save runs
    synchronously inside the grace window — size it accordingly.
    """

    def __init__(self, engine, *, signals=(signal.SIGTERM,),
                 exit_code: int = DEFAULT_EXIT_CODE,
                 exit_on_signal: bool = True,
                 save_dir: Optional[str] = None,
                 save_on_preempt: bool = False):
        if save_on_preempt and not save_dir:
            raise ValueError("save_on_preempt=True requires save_dir")
        self.engine = engine
        self.signals = tuple(signals)
        self.exit_code = exit_code
        self.exit_on_signal = exit_on_signal
        self.save_dir = save_dir
        self.save_on_preempt = save_on_preempt
        self.preempted = False
        self._prev: dict = {}

    def install(self) -> "PreemptionGuard":
        """Register the handlers (main thread only — signal.signal's own
        rule). Returns self for one-line wiring."""
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError("PreemptionGuard.install() must run on the "
                               "main thread (signal.signal requirement)")
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev = {}

    # ----------------------------------------------------------- the handler
    def _handle(self, signum, frame) -> None:
        self.preempted = True
        gp = getattr(self.engine, "goodput", None)
        gp_t0 = gp.clock() if gp is not None else 0.0
        log_dist(f"preemption: signal {signum} received — committing the "
                 "in-flight checkpoint before exit", ranks=[0],
                 level="WARNING")
        if self.save_on_preempt:
            # best-effort extra snapshot of the current state; a failure
            # here must not stop the in-flight commit from being awaited
            try:
                self.engine.save_checkpoint(self.save_dir)
            except Exception as e:
                log_dist(f"preemption: save_on_preempt failed ({e}); "
                         "falling back to the in-flight save", ranks=[0],
                         level="WARNING")
        # awaits the async commit, writes the manifest, flips 'latest'
        self.engine.wait_for_checkpoint()
        log_dist("preemption: checkpoint durable; 'latest' flipped",
                 ranks=[0], level="WARNING")
        if gp is not None:
            # the whole grace window — extra save + commit await — is
            # preemption badput in the goodput ledger's decomposition
            gp.account("preempt", gp_t0, gp.clock())
            gp.export()
        flight = getattr(self.engine, "flight", None)
        if flight is not None:
            # leave the black box next to the checkpoint: the next
            # incarnation's operator sees what the dying one was doing
            flight.note("preemption_sigterm", signum=int(signum),
                        exit_code=self.exit_code)
            flight.dump("preemption")
        if self.exit_on_signal:
            raise SystemExit(self.exit_code)
