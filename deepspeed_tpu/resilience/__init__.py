"""Resilience layer: typed failure taxonomy, deterministic fault injection,
and the guards the chaos suite proves.

Reference analog: the reference stack hardens the same seams through its
elastic agent (restart-on-membership-change) and the Nebula async
checkpoint service (durable commit markers); serving-side guards follow
the DeepSpeed-MII production deployment shape (deadlines, cancellation,
health probes). Here the failure modes are *reproducible on demand*
(``chaos.py``) so every guard has an end-to-end test:

- ``guards``     — :class:`RequestStatus` and the typed errors callers can
  catch without string-matching (:class:`QueueFullError`,
  :class:`NonFiniteLossError`, :class:`CheckpointIntegrityError`);
- ``chaos``      — seeded, config/env-gated injection points: non-finite
  logits on decode step N, hung step, process kill between the checkpoint
  state write and the ``latest`` flip, queue flood, simulated SIGTERM
  preemption. Zero overhead and inert when disabled;
- ``integrity``  — checkpoint manifests (per-file checksums, commit marker
  written last), load-time verification, newest-verified-tag fallback and
  keep-last-K pruning;
- ``preempt``    — :class:`PreemptionGuard`: SIGTERM awaits the in-flight
  async save and flips ``latest`` before exit.

See docs/RESILIENCE.md for the full guard semantics.
"""

from .chaos import ChaosConfig, ChaosMonkey, kill_point, preempt_step
from .guards import (CheckpointIntegrityError, NonFiniteLossError,
                     QueueFullError, RequestStatus)
from .integrity import (newest_verified_tag, prune_tags, verify_tag,
                        write_manifest)
from .preempt import PreemptionGuard

__all__ = [
    "RequestStatus", "QueueFullError", "NonFiniteLossError",
    "CheckpointIntegrityError",
    "ChaosConfig", "ChaosMonkey", "kill_point", "preempt_step",
    "write_manifest", "verify_tag", "newest_verified_tag", "prune_tags",
    "PreemptionGuard",
]
