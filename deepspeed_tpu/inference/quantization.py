"""Weight-only int8 quantization for inference.

Reference analog: ``deepspeed/inference/quantization/`` (int4/int8 WOQ) and
the ``GroupQuantizer`` used by kernel injection
(``module_inject/replace_module.py:43``). TPU-native: weights are stored as
int8 + per-group fp scales in HBM (4x memory cut vs bf16 at group_size -> inf)
and dequantized on the fly inside the jitted step — XLA fuses the dequant
into the consuming matmul, so HBM traffic (the decode bottleneck) drops
accordingly. Pallas int8-matmul kernels can replace the fused dequant where
profitable.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """int8 weight + per-group fp32 scales. ``group_size`` is pytree aux
    data (static under jit, so reshapes stay static-shaped)."""

    def __init__(self, q, scale, group_size: int):
        self.q = q            # int8, original shape
        self.scale = scale    # fp32, (..., n_groups, 1)
        self.group_size = group_size

    def tree_flatten(self):
        return (self.q, self.scale), self.group_size

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def shape(self):
        return self.q.shape


def quantize(w, group_size: int = 128) -> QuantizedTensor:
    """Symmetric per-group int8 quantization along the last dim."""
    shape = w.shape
    last = shape[-1]
    gs = group_size if last % group_size == 0 else last
    wf = w.astype(jnp.float32).reshape(shape[:-1] + (last // gs, gs))
    amax = jnp.max(jnp.abs(wf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q.reshape(shape), scale=scale, group_size=gs)


def dequantize(qt: QuantizedTensor, dtype=jnp.bfloat16) -> jnp.ndarray:
    shape = qt.q.shape
    last = shape[-1]
    qf = qt.q.astype(jnp.float32).reshape(
        shape[:-1] + (last // qt.group_size, qt.group_size))
    return (qf * qt.scale).reshape(shape).astype(dtype)


def _should_quantize(path, leaf, min_size: int) -> bool:
    if leaf.ndim < 2 or leaf.size < min_size:
        return False
    name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
    # norms/bias stay full precision (match the reference WOQ exclusions)
    return not (name.startswith(("ln", "b")) or "bias" in name
                or "scale" in name)


def quantize_params(params: Any, group_size: int = 128,
                    min_size: int = 4096) -> Any:
    """Quantize every large matmul weight in a param pytree to int8."""
    return jax.tree_util.tree_map_with_path(
        lambda p, leaf: quantize(leaf, group_size)
        if _should_quantize(p, leaf, min_size) else leaf, params)


def dequantize_params(params: Any, dtype=jnp.bfloat16) -> Any:
    """Inverse of :func:`quantize_params` — called inside jit so XLA fuses
    the dequant into consumers (weights stay int8 in HBM)."""
    return jax.tree.map(
        lambda leaf: dequantize(leaf, dtype)
        if isinstance(leaf, QuantizedTensor) else leaf,
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def quantized_bytes(params: Any) -> int:
    total = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.q.size + leaf.scale.size * 4
        else:
            total += leaf.size * leaf.dtype.itemsize
    return int(total)
