"""Weight-only int8/int4 quantization for inference.

Reference analog: ``deepspeed/inference/quantization/`` (int4/int8 WOQ) and
the ``GroupQuantizer`` used by kernel injection
(``module_inject/replace_module.py:43``). TPU-native: weights are stored as
int8 + per-group fp scales in HBM (4x memory cut vs bf16 at group_size -> inf)
and dequantized on the fly inside the jitted step — XLA fuses the dequant
into the consuming matmul, so HBM traffic (the decode bottleneck) drops
accordingly. Pallas int8-matmul kernels can replace the fused dequant where
profitable.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """int8 (or nibble-packed int4) weight + per-group fp32 scales.
    ``group_size`` and ``bits`` are pytree aux data (static under jit, so
    reshapes stay static-shaped). int4 packs two signed nibbles per int8
    byte along the last dim (reference ``csrc/quantization/quantize_intX``)."""

    def __init__(self, q, scale, group_size: int, bits: int = 8):
        self.q = q            # int8; original shape, or (..., last/2) packed
        self.scale = scale    # fp32, (..., n_groups, 1)
        self.group_size = group_size
        self.bits = bits

    def tree_flatten(self):
        return (self.q, self.scale), (self.group_size, self.bits)

    @classmethod
    def tree_unflatten(cls, aux, children):
        gs, bits = aux
        return cls(children[0], children[1], gs, bits)

    @property
    def shape(self):
        if self.bits == 4:
            return self.q.shape[:-1] + (self.q.shape[-1] * 2,)
        return self.q.shape


def _pack_int4(q):
    """(..., last) signed int4 values in int8 → (..., last/2) packed bytes."""
    lo = q[..., 0::2] & 0x0F
    hi = (q[..., 1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8)


def _unpack_int4(packed):
    """(..., last/2) packed bytes → (..., last) signed int4 values (int8)."""
    lo = (packed << 4).astype(jnp.int8) >> 4          # sign-extend low nibble
    hi = packed >> 4                                  # arithmetic shift: high
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(packed.shape[:-1] + (packed.shape[-1] * 2,))


def quantize(w, group_size: int = 128, bits: int = 8) -> QuantizedTensor:
    """Symmetric per-group int8/int4 quantization along the last dim.

    A leaf whose effective group size is odd cannot nibble-pack — it
    degrades to int8 instead of failing the whole model (e.g. GPT-2's odd
    50257-vocab head when the last dim isn't group-divisible)."""
    assert bits in (4, 8), bits
    shape = w.shape
    last = shape[-1]
    gs = group_size if last % group_size == 0 else last
    if bits == 4 and gs % 2 != 0:
        bits = 8
    wf = w.astype(jnp.float32).reshape(shape[:-1] + (last // gs, gs))
    qmax = 7.0 if bits == 4 else 127.0
    amax = jnp.max(jnp.abs(wf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / qmax
    q = jnp.clip(jnp.round(wf / scale), -qmax, qmax).astype(jnp.int8)
    q = q.reshape(shape)
    if bits == 4:
        q = _pack_int4(q)
    return QuantizedTensor(q=q, scale=scale, group_size=gs, bits=bits)


def dequantize(qt: QuantizedTensor, dtype=jnp.bfloat16) -> jnp.ndarray:
    if qt.bits == 4:
        qv = _unpack_int4(qt.q).astype(jnp.float32)
    else:
        qv = qt.q.astype(jnp.float32)
    shape = qv.shape
    last = shape[-1]
    qf = qv.reshape(shape[:-1] + (last // qt.group_size, qt.group_size))
    return (qf * qt.scale).reshape(shape).astype(dtype)


def _should_quantize(path, leaf, min_size: int) -> bool:
    if leaf.ndim < 2 or leaf.size < min_size:
        return False
    name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
    # norms/bias stay full precision (match the reference WOQ exclusions);
    # MoE routers too — near-tie routing decisions flap across quantization
    # rounding, same reason the engine's compute cast keeps them fp32.
    return not (name.startswith(("ln", "b")) or "bias" in name
                or "scale" in name or name == "router")


def quantize_params(params: Any, group_size: int = 128,
                    min_size: int = 4096, bits: int = 8) -> Any:
    """Quantize every large matmul weight in a param pytree to int8/int4."""
    return jax.tree_util.tree_map_with_path(
        lambda p, leaf: quantize(leaf, group_size, bits=bits)
        if _should_quantize(p, leaf, min_size) else leaf, params)


def dequantize_params(params: Any, dtype=jnp.bfloat16) -> Any:
    """Inverse of :func:`quantize_params` — called inside jit so XLA fuses
    the dequant into consumers (weights stay int8 in HBM)."""
    return jax.tree.map(
        lambda leaf: dequantize(leaf, dtype)
        if isinstance(leaf, QuantizedTensor) else leaf,
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def quantized_shardings(specs: Any, qtree: Any, mesh) -> Any:
    """Map a model's ``param_specs()`` tree onto the quantized pytree.

    The reference composes int8 with mp_size by splitting each quantized
    shard's scales alongside its weights
    (``module_inject/replace_module.py:43`` GroupQuantizer over mp ranks);
    here the same composition is a sharding rule: ``q`` takes the original
    leaf's PartitionSpec verbatim, and ``scale`` — shaped
    ``orig[:-1] + (n_groups, 1)`` — takes the same entries with the last
    dim's entry moved to the groups dim. Group boundaries align with model
    shards whenever the per-shard last dim is group-divisible (the usual
    case: d % (tp*group) == 0); when a leaf degraded to one whole-row group
    the scale is replicated over the trailing dims, which is still correct
    under GSPMD — just a broadcast at dequant."""
    def leaf_shardings(spec, q_or_leaf):
        spec = spec if spec is not None else P()
        if not isinstance(q_or_leaf, QuantizedTensor):
            return NamedSharding(mesh, spec)
        rank = len(q_or_leaf.q.shape)
        entries = tuple(spec) + (None,) * (rank - len(tuple(spec)))
        # one whole-tensor group (degraded gs): scale has a single group —
        # shard entries on a size-1 dim would be invalid, so replicate it
        n_groups = q_or_leaf.scale.shape[-2]
        scale_last = entries[-1] if n_groups > 1 else None
        return QuantizedTensor(
            q=NamedSharding(mesh, P(*entries)),
            scale=NamedSharding(mesh, P(*entries[:-1], scale_last, None)),
            group_size=q_or_leaf.group_size, bits=q_or_leaf.bits)

    return jax.tree.map(leaf_shardings, specs, qtree,
                        is_leaf=lambda x: x is None or isinstance(x, P))


def quantized_bytes(params: Any) -> int:
    total = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.q.size + leaf.scale.size * 4   # packed size for int4
        else:
            total += leaf.size * leaf.dtype.itemsize
    return int(total)
