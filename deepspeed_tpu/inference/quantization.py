"""Weight-only int8/int4 quantization for inference — int8 end-to-end.

Reference analog: ``deepspeed/inference/quantization/`` (int4/int8 WOQ) and
the ``GroupQuantizer`` used by kernel injection
(``module_inject/replace_module.py:43``). TPU-native: weights are stored as
int8 (or nibble-packed int4) + per-channel fp32 group scales in HBM and are
consumed *quantized* by the decode step — either by the fused Pallas GEMM
(``ops/woq_matmul.py``: int8 tiles dequantized in VMEM inside the matmul
loop, the in-kernel design of ``csrc/transformer/inference/``) or, off-TPU
and for kernel-ineligible leaves, by a per-use XLA dequant at the point of
consumption. The previous whole-matrix ``dequantize_params`` hoist — which
let XLA materialize a bf16 copy outside the decode scan and re-read *that*
(``WOQ_PROBE.json`` round 5: int8 decode slower than bf16) — is gone from
the decode path; it survives only for the cold full-forward.

Layout: groups of ``group_size`` rows along the weight's second-to-last
dim (the contraction dim of an ``x @ W`` projection) share one scale row:
``scale`` is ``(..., G, N)`` fp32 — per-channel along N, grouped along K.
This is the layout that lets the fused GEMM fold the scale *outside* the
int8 dot (one ``(1, bn)`` multiply per k-step) instead of dequantizing
whole tiles. int4 packs two signed nibbles per byte along *adjacent rows*
of the grouped dim (sublane-interleave unpack — Mosaic-friendly).
"""

from __future__ import annotations

from functools import reduce
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """int8 (or row-pair-packed int4) weight + per-channel group scales.

    ``q``: original shape, or ``(..., K/2, N)`` packed for int4;
    ``scale``: ``(..., G, N)`` fp32 with ``G = K / group_size`` groups
    along the second-to-last dim. ``group_size``/``bits``/``pspec`` are
    pytree aux data (static under jit). ``pspec`` carries the leaf's
    ``param_specs()`` PartitionSpec so the consumption-side dispatcher can
    wrap the Pallas GEMM in the right shard_map under tensor parallelism —
    the sharding rule travels WITH the weight, the way the reference's
    GroupQuantizer splits scales alongside their mp-sharded weights."""

    def __init__(self, q, scale, group_size: int, bits: int = 8,
                 pspec: Optional[P] = None):
        self.q = q
        self.scale = scale
        self.group_size = group_size
        self.bits = bits
        self.pspec = pspec

    def tree_flatten(self):
        return (self.q, self.scale), (self.group_size, self.bits, self.pspec)

    @classmethod
    def tree_unflatten(cls, aux, children):
        gs, bits, pspec = aux
        return cls(children[0], children[1], gs, bits, pspec)

    @property
    def shape(self):
        if self.bits == 4:
            return (self.q.shape[:-2]
                    + (self.q.shape[-2] * 2, self.q.shape[-1]))
        return self.q.shape


def _pack_int4(q):
    """(..., K, N) signed int4 values in int8 → (..., K/2, N): adjacent
    rows pack as (low nibble = even row, high nibble = odd row)."""
    lo = q[..., 0::2, :] & 0x0F
    hi = (q[..., 1::2, :] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8)


def _unpack_int4(packed):
    """(..., K/2, N) packed bytes → (..., K, N) signed int4 values
    (int8), interleaving the row pairs back."""
    lo = (packed << 4).astype(jnp.int8) >> 4          # sign-extend low
    hi = packed >> 4                                  # arithmetic: high
    out = jnp.stack([lo, hi], axis=-2)                # (..., K/2, 2, N)
    return out.reshape(packed.shape[:-2]
                       + (packed.shape[-2] * 2, packed.shape[-1]))


def quantize(w, group_size: int = 128, bits: int = 8,
             pspec: Optional[P] = None) -> QuantizedTensor:
    """Symmetric int8/int4 quantization, groups along the second-to-last
    dim, scales per-channel along the last dim.

    A leaf whose second-to-last dim isn't group-divisible degrades to one
    whole group (e.g. GPT-2's odd 50257-row vocab table); a group that
    can't row-pack (odd size) degrades int4 → int8 per leaf instead of
    failing the whole model."""
    assert bits in (4, 8), bits
    shape = w.shape
    K, N = shape[-2], shape[-1]
    gs = group_size if K % group_size == 0 else K
    if bits == 4 and gs % 2 != 0:
        bits = 8
    G = K // gs
    wf = w.astype(jnp.float32).reshape(shape[:-2] + (G, gs, N))
    qmax = 7.0 if bits == 4 else 127.0
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)   # (..., G, 1, N)
    scale = jnp.maximum(amax, 1e-8) / qmax
    q = jnp.clip(jnp.round(wf / scale), -qmax, qmax).astype(jnp.int8)
    q = q.reshape(shape)
    if bits == 4:
        q = _pack_int4(q)
    return QuantizedTensor(q=q, scale=scale[..., 0, :], group_size=gs,
                           bits=bits, pspec=pspec)


def dequantize(qt: QuantizedTensor, dtype=jnp.bfloat16) -> jnp.ndarray:
    qv = _unpack_int4(qt.q) if qt.bits == 4 else qt.q
    shape = qv.shape
    K, N = shape[-2], shape[-1]
    G = K // qt.group_size
    qf = qv.astype(jnp.float32).reshape(shape[:-2] + (G, qt.group_size, N))
    out = qf * qt.scale[..., :, None, :]
    return out.reshape(shape).astype(dtype)


def dequant_rows(qt: QuantizedTensor, ids, dtype=jnp.bfloat16):
    """Gather + dequantize only the rows named by ``ids`` — the embedding
    lookup of an int8-stored table reads int8 bytes for exactly the batch's
    tokens instead of materializing the dense table. qt: 2-D (V, N)."""
    if qt.bits == 4:
        pr = qt.q[ids // 2]                           # (..., N) packed
        lo = (pr << 4).astype(jnp.int8) >> 4
        hi = pr >> 4
        rows = jnp.where((ids % 2 == 0)[..., None], lo, hi)
    else:
        rows = qt.q[ids]
    G = qt.scale.shape[-2]
    g = ids // qt.group_size if G > 1 else jnp.zeros_like(ids)
    return (rows.astype(jnp.float32) * qt.scale[g]).astype(dtype)


# ----------------------------------------------------------- consumption
def _mesh_tp():
    from ..platform.mesh import current_mesh

    mesh = current_mesh()
    if mesh is None or "model" not in getattr(mesh, "axis_names", ()):
        return None, 1
    return mesh, int(mesh.shape["model"])


def _has_model(entry) -> bool:
    names = entry if isinstance(entry, (tuple, list)) else (entry,)
    return "model" in names


def woq_dot(x, qt: QuantizedTensor, use_kernel: bool = False,
            out_dtype=None):
    """``x @ W`` for a quantized ``(K, N)`` weight (leading x dims free).

    ``use_kernel=True`` routes eligible leaves through the fused Pallas
    GEMM (int8 stays int8 all the way into VMEM); otherwise — and for
    kernel-ineligible layouts — the weight is dequantized per-use at the
    point of consumption (XLA may fuse the convert into the operand load;
    on TPU prefer the kernel, which makes the fusion non-optional).

    Under a tensor-parallel mesh the kernel call is shard_mapped according
    to the weight's travelling ``pspec``: column-sharded weights run
    shard-local with no collective; row-sharded (contraction-split)
    weights psum their fp32 partials — the same math GSPMD emits for the
    dense path."""
    from ..ops.woq_matmul import woq_matmul, woq_matmul_eligible

    K = x.shape[-1]
    N = qt.shape[-1]
    gs, bits = qt.group_size, qt.bits
    out_dtype = out_dtype or x.dtype
    if (not use_kernel) or qt.q.ndim != 2 \
            or not woq_matmul_eligible(K, gs, bits):
        return jax.lax.dot_general(
            x, dequantize(qt, x.dtype), (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=out_dtype)
    x2 = x.reshape(-1, K)
    G = qt.scale.shape[-2]

    mesh, tp = _mesh_tp()
    spec = qt.pspec
    ent = tuple(spec)[-2:] if spec is not None and len(tuple(spec)) >= 2 \
        else (None, None)
    if tp > 1 and _has_model(ent[1]):
        if N % tp != 0:
            # shard_map needs even shards (GSPMD tolerated uneven); the
            # per-use dequant keeps such configs serving
            return jax.lax.dot_general(
                x, dequantize(qt, x.dtype),
                (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=out_dtype)
        # column-sharded (wqkv/w_in/w_gate): shard-local columns, no
        # collective; scale columns shard identically
        fn = jax.shard_map(
            lambda xs, qs, ss: woq_matmul(xs, qs, ss, group_size=gs,
                                          bits=bits, out_dtype=out_dtype),
            mesh=mesh, in_specs=(P(None, None), P(None, "model"),
                                 P(None, "model")),
            out_specs=P(None, "model"), check_vma=False)
        out2 = fn(x2, qt.q, qt.scale)
    elif tp > 1 and _has_model(ent[0]):
        qrows = qt.q.shape[0]
        if (G % tp != 0 and G != 1) or qrows % tp != 0 \
                or x2.shape[1] % tp != 0:
            return jax.lax.dot_general(
                x, dequantize(qt, x.dtype),
                (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=out_dtype)
        # row-sharded (wo/w_out): contraction splits, fp32 partials psum.
        # A degraded single group (G == 1) replicates its scale row and
        # each shard treats its local row count as the group — the scale
        # is constant over all rows, so the math is identical.
        if G == 1:
            s_spec, gs_local = P(None, None), K // tp
        else:
            s_spec, gs_local = P("model", None), gs

        def body(xs, qs, ss):
            part = woq_matmul(xs, qs, ss, group_size=gs_local, bits=bits,
                              out_dtype=jnp.float32)
            return jax.lax.psum(part, "model").astype(out_dtype)

        fn = jax.shard_map(body, mesh=mesh,
                           in_specs=(P(None, "model"), P("model", None),
                                     s_spec),
                           out_specs=P(None, None), check_vma=False)
        out2 = fn(x2, qt.q, qt.scale)
    else:
        out2 = woq_matmul(x2, qt.q, qt.scale, group_size=gs, bits=bits,
                          out_dtype=out_dtype)
    return out2.reshape(x.shape[:-1] + (N,))


def woq_dot_t(x, qt: QuantizedTensor, use_kernel: bool = False,
              out_dtype=None):
    """``x @ W.T`` for a quantized ``(V, K)`` weight — the tied-embedding
    unembedding, consumed in table layout. Returns (..., V) in
    ``out_dtype`` (default ``x.dtype``; the decode head asks for fp32 so
    the sampler never round-trips through bf16)."""
    from ..ops.woq_matmul import woq_matmul_t, woq_matmul_t_eligible

    K = x.shape[-1]
    V = qt.shape[-2]
    gs, bits = qt.group_size, qt.bits
    out_dtype = out_dtype or x.dtype
    if (not use_kernel) or qt.q.ndim != 2 \
            or not woq_matmul_t_eligible(V, K, gs, bits):
        w = dequantize(qt, x.dtype)
        return jax.lax.dot_general(x, w, (((x.ndim - 1,), (1,)), ((), ())),
                                   preferred_element_type=out_dtype)
    x2 = x.reshape(-1, K)
    G = qt.scale.shape[-2]

    mesh, tp = _mesh_tp()
    spec = qt.pspec
    ent = tuple(spec)[-2:] if spec is not None and len(tuple(spec)) >= 2 \
        else (None, None)
    if tp > 1 and _has_model(ent[0]) and V % tp == 0 \
            and (G % tp == 0 or G == 1) and qt.q.shape[0] % tp == 0:
        # vocab-sharded table: shard-local output columns. A degraded
        # single-group table (vocab not group-divisible) replicates its
        # one scale row; each shard's local vocab IS its group then —
        # the whole-table dequant this path replaces is the single
        # largest per-step weight read of a tied-head model.
        if G == 1:
            s_spec, gs_local = P(None, None), V // tp
        else:
            s_spec, gs_local = P("model", None), gs
        fn = jax.shard_map(
            lambda xs, qs, ss: woq_matmul_t(xs, qs, ss, group_size=gs_local,
                                            bits=bits, out_dtype=out_dtype),
            mesh=mesh, in_specs=(P(None, None), P("model", None), s_spec),
            out_specs=P(None, "model"), check_vma=False)
        out2 = fn(x2, qt.q, qt.scale)
    elif tp > 1 and spec is not None and any(map(_has_model, ent)):
        w = dequantize(qt, x.dtype)
        return jax.lax.dot_general(x, w, (((x.ndim - 1,), (1,)), ((), ())),
                                   preferred_element_type=out_dtype)
    else:
        out2 = woq_matmul_t(x2, qt.q, qt.scale, group_size=gs, bits=bits,
                            out_dtype=out_dtype)
    return out2.reshape(x.shape[:-1] + (V,))


def matmul_any(x, w, use_kernel: bool = False):
    """``x @ w`` whether ``w`` is dense or a :class:`QuantizedTensor` —
    the one dispatch point every decode-path projection goes through."""
    if isinstance(w, QuantizedTensor):
        return woq_dot(x, w, use_kernel=use_kernel)
    return x @ w.astype(x.dtype)


def tp_quant_dot(x, w, bits: int = 8):
    """``x @ w`` for a DENSE row-sharded (contraction-split) weight with
    the ``model``-axis partial-sum reduction spelled as an explicit
    EQuARX-style two-sided int8 all-reduce
    (``comm.compressed.int8_psum``) instead of the fp psum GSPMD
    inserts — the quantized TP decode collective
    (``inference.tp_comm_quant``).

    Local partials accumulate in fp32 (``preferred_element_type``), the
    wire carries int8 payloads + fp32 block scales on both hops, and the
    result is cast back to ``x.dtype``. Returns ``None`` when the
    explicit spelling doesn't apply — no TP mesh in context, or the
    contraction dim doesn't shard evenly — and the caller falls back to
    the plain GSPMD matmul (same program as the knob-off path)."""
    if bits != 8:
        raise ValueError(f"tp_quant_dot supports int8 only, got {bits}")
    mesh, tp = _mesh_tp()
    if tp <= 1:
        return None
    K = x.shape[-1]
    N = w.shape[-1]
    if K % tp != 0:
        return None
    from ..comm.compressed import int8_psum

    x2 = x.reshape(-1, K)

    def body(xs, ws):
        part = jax.lax.dot_general(
            xs, ws.astype(xs.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return int8_psum(part, "model").astype(x.dtype)

    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(None, "model"), P("model", None)),
                       out_specs=P(None, None), check_vma=False)
    return fn(x2, w).reshape(x.shape[:-1] + (N,))


# ------------------------------------------------------------- pytree ops
def _should_quantize(path, leaf, min_size: int) -> bool:
    if leaf.ndim < 2 or leaf.size < min_size:
        return False
    name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
    # norms/bias stay full precision (match the reference WOQ exclusions);
    # MoE routers too — near-tie routing decisions flap across quantization
    # rounding, same reason the engine's compute cast keeps them fp32.
    return not (name.startswith(("ln", "b")) or "bias" in name
                or "scale" in name or name == "router")


def _spec_at(specs: Any, path):
    """Walk a matching specs pytree by a tree_map_with_path key path."""
    if specs is None:
        return None
    try:
        return reduce(lambda t, k: t[getattr(k, "key", getattr(
            k, "idx", None))], path, specs)
    except (KeyError, TypeError, IndexError):
        return None


def quantize_params(params: Any, group_size: int = 128,
                    min_size: int = 4096, bits: int = 8,
                    specs: Any = None) -> Any:
    """Quantize every large matmul weight in a param pytree to int8/int4.
    ``specs`` (a matching ``param_specs()`` tree) stamps each quantized
    leaf's PartitionSpec into its aux data for the TP-aware dispatcher."""
    return jax.tree_util.tree_map_with_path(
        lambda p, leaf: quantize(leaf, group_size, bits=bits,
                                 pspec=_spec_at(specs, p))
        if _should_quantize(p, leaf, min_size) else leaf, params)


def dequantize_params(params: Any, dtype=jnp.bfloat16) -> Any:
    """Materialize every quantized leaf — the COLD path only (one-shot
    full forward, prefill). The decode scan consumes leaves quantized via
    :func:`matmul_any` / :func:`woq_dot_t` / :func:`dequant_rows`."""
    return jax.tree.map(
        lambda leaf: dequantize(leaf, dtype)
        if isinstance(leaf, QuantizedTensor) else leaf,
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def quantized_shardings(specs: Any, qtree: Any, mesh) -> Any:
    """Map a model's ``param_specs()`` tree onto the quantized pytree.

    ``q`` takes the original leaf's PartitionSpec verbatim (int4's packed
    row dim halves the row count; row-sharding stays valid when the
    per-shard row count is even — the usual d % (2*tp) == 0 case).
    ``scale`` — shaped ``orig[:-2] + (G, N)`` — takes the same entries
    with the second-to-last (grouped-dim) entry kept on G when G > 1 and
    dropped (replicated) when the leaf degraded to one whole group, where
    a sharded size-1 dim would be invalid."""
    def leaf_shardings(spec, q_or_leaf):
        spec = spec if spec is not None else P()
        if not isinstance(q_or_leaf, QuantizedTensor):
            return NamedSharding(mesh, spec)
        rank = len(q_or_leaf.q.shape)
        entries = tuple(spec) + (None,) * (rank - len(tuple(spec)))
        n_groups = q_or_leaf.scale.shape[-2]
        group_entry = entries[-2] if n_groups > 1 else None
        return QuantizedTensor(
            q=NamedSharding(mesh, P(*entries)),
            scale=NamedSharding(mesh, P(*entries[:-2], group_entry,
                                        entries[-1])),
            group_size=q_or_leaf.group_size, bits=q_or_leaf.bits,
            pspec=q_or_leaf.pspec)

    return jax.tree.map(leaf_shardings, specs, qtree,
                        is_leaf=lambda x: x is None or isinstance(x, P))


def quantized_bytes(params: Any) -> int:
    total = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.q.size + leaf.scale.size * 4   # packed for int4
        else:
            total += leaf.size * leaf.dtype.itemsize
    return int(total)


def decode_weight_bytes(params: Any, skip: tuple = ("pos_embed",)) -> int:
    """Model of the weight HBM bytes one decode step re-reads: every
    matmul weight streams fully per token (int8/int4 leaves count their
    quantized bytes + scales — the fused GEMM's whole point); embedding
    *lookups* are row gathers, not full reads, so positional tables are
    skipped. A TIED token table is read fully — by the unembedding
    matmul — and counts once; an untied model's unembedding read is its
    ``lm_head``, so there ``tok_embed`` is gather-only and skipped too."""
    if isinstance(params, dict) and "lm_head" in params:
        skip = skip + ("tok_embed",)
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor))[0]:
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in skip:
            continue
        if isinstance(leaf, QuantizedTensor):
            total += leaf.q.size + leaf.scale.size * 4
        elif leaf.ndim >= 2:
            total += leaf.size * leaf.dtype.itemsize
    return int(total)
