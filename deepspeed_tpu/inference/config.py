"""Inference config (reference ``deepspeed/inference/config.py:128-304``).

The knobs that survive the TPU translation: dtype, tensor parallel size,
max output tokens, weight-only quantization. ``enable_cuda_graph`` and
``replace_with_kernel_inject`` have no analog — XLA compilation subsumes
graph capture, and the model is functional so there is nothing to inject.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax.numpy as jnp

_DTYPES = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
           "float32": jnp.float32, "fp32": jnp.float32,
           "float16": jnp.float16, "fp16": jnp.float16}


@dataclasses.dataclass
class InferenceConfig:
    dtype: str = "bfloat16"            # compute dtype for decode
    tensor_parallel: int = 1           # reference tensor_parallel.tp_size
    expert_parallel: int = 1           # reference moe.ep_size: experts served
                                       # sharded over the mesh 'expert' axis
    max_out_tokens: int = 256          # reference max_out_tokens
    quantize: bool = False             # weight-only quant (WOQ)
    quant_group_size: int = 128
    quant_bits: int = 8                # 8 or 4 (nibble-packed)
    eos_token_id: Optional[int] = None
    seed: int = 0
    # Pallas streaming cache-attention for the 1-token decode step
    # (ops/decode_attention.py). None = auto: on for TPU, off elsewhere
    # (interpret-mode Pallas inside the decode scan is test-only slow).
    flash_decode: Optional[bool] = None
    # WOQ only: route eligible quantized projections through the fused
    # Pallas dequant-in-VMEM GEMM (ops/woq_matmul.py) so decode reads
    # int8/int4 bytes from HBM by construction. None = auto: on for TPU,
    # off elsewhere (the XLA per-use dequant is the portable fallback).
    woq_kernel: Optional[bool] = None
    # Subsumed knob, accepted for config compat: decode now keeps weights
    # quantized end-to-end and dispatches the dequant at each consumption
    # site, so there is no hoisted whole-tree dequant to toggle anymore
    # (round-5 WOQ_PROBE showed XLA hoisting it either way).
    dequant_per_step: bool = False
    # Request tracing (observability/tracing.py): every generate() records
    # TTFT, per-token decode latency, tokens/s, and roofline MBU into a
    # ring buffer surfaced by InferenceEngine.metrics_snapshot(). When on,
    # generation compiles as two programs (prefill / decode scan) and pays
    # ONE extra host sync per request — never one per token. When off
    # (default), generate() keeps the single fused program and adds no
    # host synchronization at all.
    observability: bool = False
    trace_ring_size: int = 256

    def flash_decode_resolved(self) -> bool:
        if self.flash_decode is not None:
            return self.flash_decode
        import jax

        return jax.default_backend() == "tpu"

    def woq_kernel_resolved(self) -> bool:
        if self.woq_kernel is not None:
            return self.woq_kernel
        import jax

        return jax.default_backend() == "tpu"

    @classmethod
    def from_any(cls, cfg: "InferenceConfig | dict | None") -> "InferenceConfig":
        if cfg is None:
            return cls()
        if isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        flat = dict(cfg)
        # accept the reference's nested {"tensor_parallel": {"tp_size": N}}
        tp = flat.get("tensor_parallel")
        if isinstance(tp, dict):
            flat["tensor_parallel"] = int(tp.get("tp_size", 1))
        # accept the reference's {"moe": {"ep_size": N}} nesting — with the
        # same strictness as top-level keys (a typo'd sub-key must raise,
        # not silently serve with expert_parallel=1)
        moe = flat.pop("moe", None)
        if moe is not None:
            if not isinstance(moe, dict):
                raise ValueError("inference config 'moe' must be a dict "
                                 f"like {{'ep_size': N}}, got {moe!r}")
            unknown_moe = set(moe) - {"ep_size"}
            if unknown_moe:
                raise ValueError(f"unknown moe config keys: {sorted(unknown_moe)}")
            flat.setdefault("expert_parallel", int(moe.get("ep_size", 1)))
        unknown = set(flat) - known
        if unknown:
            raise ValueError(f"unknown inference config keys: {sorted(unknown)}")
        return cls(**flat)

    @property
    def compute_dtype(self) -> Any:
        return _DTYPES[self.dtype]
