"""Inference config (reference ``deepspeed/inference/config.py:128-304``).

The knobs that survive the TPU translation: dtype, tensor parallel size,
max output tokens, weight-only quantization. ``enable_cuda_graph`` and
``replace_with_kernel_inject`` have no analog — XLA compilation subsumes
graph capture, and the model is functional so there is nothing to inject.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax.numpy as jnp

_DTYPES = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
           "float32": jnp.float32, "fp32": jnp.float32,
           "float16": jnp.float16, "fp16": jnp.float16}


@dataclasses.dataclass
class ServingConfig:
    """Continuous-batching knobs (``deepspeed_tpu/serving/``).

    The compiled-program budget is a direct function of these: steady-state
    serving runs one slot decode program, one slot-insert program, and one
    prefill-chunk program per chunk bucket (powers of two from 8 up to
    ``prefill_chunk``) — see docs/SERVING.md for bucket-tuning guidance.
    """

    slots: int = 8                  # persistent KV slots (decode batch)
    max_len: int = 256              # per-slot cache capacity (prompt + new);
                                    # serving admits only P + max_new <= max_len
    prefill_chunk: int = 32         # SplitFuse-style chunk size: long prompts
                                    # prefill in chunks of this many tokens,
                                    # one chunk per scheduler iteration,
                                    # interleaved with the slot decode step
    max_queue: int = 0              # submit() backpressure; 0 = unbounded
    # ---- paged KV cache (serving/pages.py, docs/SERVING.md) ----
    # page_size > 0 replaces the contiguous per-slot cache with a pooled
    # (L, pages, KV, page_size, hd) page cache: per-slot integer page
    # tables indexed inside the attention read, a host-side radix prefix
    # tree sharing identical prompt prefixes copy-free across slots
    # (refcounted pages, copy-on-write at the first divergent page), and
    # typed PagePoolExhausted admission control instead of mid-decode
    # OOM. 0 (default) keeps the contiguous cache — bit-for-bit the
    # pre-paging engine, same program set.
    page_size: int = 0              # tokens per KV page; must divide max_len
    pool_pages: int = 0             # pool size incl. the reserved scratch
                                    # page; 0 = auto (1 + slots * pages/slot)
    prefix_sharing: bool = True     # radix-tree prefix reuse (paged only)
    # int8 quantized KV: pool stored int8 with per-token per-head scales,
    # quantized on append, dequantized at the attention read (the WOQ
    # point-of-use discipline applied to the cache). 0 = fp pool at the
    # engine compute dtype (the bit-parity path).
    kv_quant_bits: int = 0
    # ---- tiered KV: pinned-host page store (serving/hostkv.py) ----
    # host_pool_bytes > 0 (paged only) bounds a host-memory tier that
    # keeps evicted tree-held pages instead of dropping them: eviction
    # demotes full-block entries (data + int8 scale planes + the token
    # prefix that keys them), admission consults the tier right after
    # the radix-tree match, and matched cold prefixes restore by async
    # H2D copy into the prefill cache — resume pays copy bandwidth, not
    # recompute FLOPs. fp restore is bit-identical to recompute; lost/
    # corrupt/pruned host copies degrade to recompute, never crash.
    # 0 (default) builds no tier: one `is not None` per admission and
    # per eviction pass, zero new programs (docs/SERVING.md).
    host_pool_bytes: int = 0
    # ---- NVMe rung below the host tier (serving/tiering.py) ----
    # nvme_pool_bytes > 0 (requires host_pool_bytes) adds a disk rung:
    # host-tier prune victims SPILL to swap files via ops/aio.py async
    # writes instead of vanishing, and admission matches promote
    # NVMe→host→HBM through the same restore path with the same
    # CRC/fallback-to-recompute contract — session residency bounded by
    # disk, not DRAM. nvme_path picks the mount (default $TMPDIR/
    # dstpu_kv_nvme; each engine gets a private subdirectory).
    nvme_pool_bytes: int = 0
    nvme_path: "str | None" = None
    # demote_ahead_idle_s > 0 (requires host_pool_bytes) turns on the
    # background demotion lane: tree-held pages idle past this many
    # seconds are proactively staged into the tier OFF the admission
    # path, so a later eviction under pressure frees pages already
    # copied (a refcount drop, not a blocking gather+device_get —
    # measured in Serve/host_tier_demote_wait_s). 0 = off.
    demote_ahead_idle_s: float = 0.0
    # engine-wide sampling policy (per-request RNG still makes every
    # request's draws independent of batch composition)
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    greedy: bool = False
    # ---- request guards (resilience layer, docs/RESILIENCE.md) ----
    # Default per-request deadlines on the serving clock, in seconds
    # (0 = none; submit() accepts per-request overrides). TTFT is measured
    # submit → first token (queue wait included); total is submit → retire.
    # Expired requests finish with RequestStatus.TIMEOUT.
    ttft_deadline_s: float = 0.0
    total_deadline_s: float = 0.0
    # Decode-step watchdog: a serving decode step whose wall time exceeds
    # this logs + counts Serve/watchdog_stalls and flips health() to
    # degraded (0 = off). Measured around the step's EXISTING host
    # read-back — the watchdog adds no syncs.
    watchdog_s: float = 0.0
    # Deterministic fault injection (resilience.chaos.ChaosConfig | dict).
    # None/disabled = the engine builds no chaos machinery at all.
    chaos: "object | None" = None
    # ---- observability: spans / flight recorder / SLOs ----
    # Lifecycle span events (observability/spans.py): queued → prefill
    # chunks → slot placement → decode residency → retired(status), plus
    # per-step and occupancy events. Host-side ring only — zero added
    # device syncs and zero new compiled programs (the bench compile
    # freeze stays the acceptance gate). Off by default.
    spans: bool = False
    spans_ring: int = 4096
    # Flight recorder (observability/flight.py): when set, the engine
    # keeps a black box (span ring + metric snapshots + recent request
    # records) and dumps it to this directory on a watchdog stall or on
    # flight.dump(). None = no recorder built.
    flight_dir: "str | None" = None
    flight_max_dumps: int = 8
    # Declarative SLO targets + anomaly detection
    # (observability.slo.SLOConfig | dict): TTFT/TPOT p99 targets and
    # error budget scored into Serve/slo_*_burn gauges, a median+MAD
    # decode-step regression detector, and a compile-storm detector.
    # None = no scoring machinery built.
    slo: "object | None" = None
    # Traffic analytics on the admission path
    # (observability.workload.WorkloadConfig | dict): prefix-overlap /
    # self-speculation estimators + shape histograms into
    # Serve/workload_*, feeding the capacity advisor
    # (observability/capacity.py). Host-side only — zero new compiled
    # programs, zero device syncs. None = no analyzer built.
    workload: "object | None" = None
    # KV residency observatory (observability/kvscope.py |
    # observability.kvscope.KVScopeConfig | dict): ghost-tree
    # eviction-regret ledger on the page pool (every prefill token
    # re-paid because of a past eviction counted and attributed),
    # per-session lifecycle heat tracking (idle/resume histograms, HBM
    # byte-seconds-held-while-idle), and the measured inputs of the
    # tiered_kv capacity-advisor lever. Host-side only — zero new
    # compiled programs, zero device syncs (the copy-bandwidth probe
    # runs only when a capacity report asks). None (default) builds
    # nothing: one `is not None` per admission/retirement/eviction.
    kvscope: "object | None" = None
    # Draft-free self-speculative decoding
    # (inference.speculation.SpeculationConfig | dict): per-slot n-gram
    # prompt-lookup drafting + one fixed-shape length-(max_draft+1)
    # verify forward per decode step, with page-table-aware rollback of
    # rejected tokens. Requires greedy sampling (the serving engine
    # enforces it — greedy spec-on is bit-identical to greedy spec-off).
    # None (default) builds nothing: the decode lane stays the plain
    # one-token step.
    speculation: "object | None" = None
    # Goodput/badput wall-time attribution (observability/goodput.py):
    # decomposes elapsed wall time into productive decode/prefill vs
    # badput buckets (compile, queue-empty idle, watchdog stall, drain,
    # ...) as Serve/goodput_* gauges + the /goodput endpoint. Costs two
    # host clock reads per iteration when on; False (default) builds no
    # ledger — zero clock reads, zero programs.
    goodput: bool = False
    # Traffic capture (observability/replay.py): record every admitted
    # submit (relative time, prompt ids, seed, session, deadline
    # overrides), terminal result (the parity oracle's reference
    # tokens), and fleet chaos event into a bounded host ring — the
    # record half of record→replay. Flight/incident dumps bundle the
    # ring's tail as traffic_trace.jsonl. False (default) builds no
    # capture at all — one `is not None` per submit/retire, zero
    # programs, zero syncs.
    capture: bool = False
    capture_ring: int = 4096
    # Arrival & scaling observatory
    # (observability.loadscope.LoadScopeConfig | dict): rolling arrival
    # rate / burstiness / token-demand / trend estimators on the submit
    # path, queueing-model utilization from span-measured service rates,
    # SLO time-to-violation forecasting, and the scaling what-ifs the
    # capacity advisor's `scaling` lever + GET /scaling report. Host-side
    # only — zero new compiled programs; readout math runs at scrape
    # cadence, never per token. None (default) builds nothing: one
    # `is not None` per submit.
    loadscope: "object | None" = None
    # Per-tenant cost attribution, fairness & noisy-neighbor observatory
    # (observability.tenantscope.TenantScopeConfig | dict): a ledger
    # keyed by Request.tenant_id on the injectable clock — tokens,
    # queue-wait/TTFT/TPOT reservoirs, KV page-seconds (PagePool hook),
    # resident tier bytes (TierStore owner accounting), per-tenant
    # prefix overlap, Jain fairness, and an edge-triggered
    # noisy-neighbor detector that marks the flight ring and dumps a
    # per-tenant breakdown into incident dirs. Host-side only — zero
    # new compiled programs; per-tenant sums conserve the fleet totals
    # exactly. None (default) builds nothing: one `is not None` per
    # submit/admission/retirement.
    tenantscope: "object | None" = None
    # Elastic fleet autoscaler (serving.autoscaler.AutoscaleConfig |
    # dict): the actuation loop over the loadscope scaling report —
    # hysteresis-guarded add/drain-then-remove/rebalance with a flap
    # budget, incident cooldown latch, drain-before-remove, and a typed
    # decision audit ring (GET/POST /autoscale). Fleet-level: a solo
    # ServingEngine ignores it. None (default) builds nothing — the
    # fleet pays one `is not None` per step, zero threads/programs.
    autoscale: "object | None" = None
    # Live telemetry & control plane
    # (observability.server.TelemetryConfig | dict): an HTTP ops surface
    # (/metrics /healthz /readyz /requests /capacity /goodput /flight +
    # token-gated POST /drain /flight/dump /slo/reload) on a daemon
    # thread, loopback-bound by default. None / enabled=False (default)
    # builds nothing — zero threads, zero programs, zero syncs; the
    # bench_serving --smoke compile freeze is the oracle. Engines can
    # also start it explicitly via engine.serve_telemetry(port=0).
    telemetry: "object | None" = None

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError(f"serving needs >= 1 slot, got {self.slots}")
        c = self.prefill_chunk
        if c < 8 or (c & (c - 1)) != 0:
            raise ValueError(
                f"prefill_chunk must be a power of two >= 8 (the chunk "
                f"bucket set), got {c}")
        if self.max_len < c:
            raise ValueError(f"max_len={self.max_len} < prefill_chunk={c}")
        if self.page_size:
            if self.page_size < 8 or self.max_len % self.page_size != 0:
                raise ValueError(
                    f"page_size must be >= 8 and divide max_len="
                    f"{self.max_len}, got {self.page_size}")
            per_slot = self.max_len // self.page_size
            if self.pool_pages == 0:
                # auto: every slot coverable with zero sharing, + scratch
                self.pool_pages = 1 + self.slots * per_slot
            elif self.pool_pages < 2:
                # smaller-than-worst-case pools are LEGAL (overcommit:
                # admission defers on transient pressure and sheds typed
                # PagePoolExhausted for requests that can never fit) —
                # but there must be at least one usable page + scratch
                raise ValueError(
                    f"pool_pages={self.pool_pages} < 2 (one usable page "
                    "+ the reserved scratch page)")
        if self.kv_quant_bits not in (0, 8):
            raise ValueError(f"kv_quant_bits must be 0 (off) or 8, "
                             f"got {self.kv_quant_bits}")
        if self.kv_quant_bits and not self.page_size:
            raise ValueError("kv_quant_bits requires the paged KV cache "
                             "(set serving.page_size)")
        if self.host_pool_bytes < 0:
            raise ValueError(f"host_pool_bytes must be >= 0, "
                             f"got {self.host_pool_bytes}")
        if self.host_pool_bytes and not self.page_size:
            raise ValueError("host_pool_bytes (the tiered host KV store) "
                             "requires the paged KV cache (set "
                             "serving.page_size)")
        if self.nvme_pool_bytes < 0:
            raise ValueError(f"nvme_pool_bytes must be >= 0, "
                             f"got {self.nvme_pool_bytes}")
        if self.nvme_pool_bytes and not self.host_pool_bytes:
            raise ValueError("nvme_pool_bytes (the NVMe KV rung) requires "
                             "the host tier above it (set "
                             "serving.host_pool_bytes)")
        if self.demote_ahead_idle_s < 0:
            raise ValueError(f"demote_ahead_idle_s must be >= 0, "
                             f"got {self.demote_ahead_idle_s}")
        if self.demote_ahead_idle_s and not self.host_pool_bytes:
            raise ValueError("demote_ahead_idle_s (background demotion) "
                             "requires the tiered host KV store (set "
                             "serving.host_pool_bytes)")
        for knob in ("ttft_deadline_s", "total_deadline_s", "watchdog_s"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0, "
                                 f"got {getattr(self, knob)}")
        if self.chaos is not None:
            from ..resilience.chaos import ChaosConfig

            self.chaos = ChaosConfig.from_any(self.chaos)
        if self.spans_ring < 1:
            raise ValueError(f"spans_ring must be >= 1, "
                             f"got {self.spans_ring}")
        if self.capture_ring < 1:
            raise ValueError(f"capture_ring must be >= 1, "
                             f"got {self.capture_ring}")
        if self.slo is not None:
            from ..observability.slo import SLOConfig

            self.slo = SLOConfig.from_any(self.slo)
        if self.workload is not None:
            from ..observability.workload import WorkloadConfig

            self.workload = WorkloadConfig.from_any(self.workload)
        if self.kvscope is not None:
            from ..observability.kvscope import KVScopeConfig

            self.kvscope = KVScopeConfig.from_any(self.kvscope)
        if self.speculation is not None:
            from .speculation import SpeculationConfig

            self.speculation = SpeculationConfig.from_any(self.speculation)
        if self.loadscope is not None:
            from ..observability.loadscope import LoadScopeConfig

            self.loadscope = LoadScopeConfig.from_any(self.loadscope)
        if self.tenantscope is not None:
            from ..observability.tenantscope import TenantScopeConfig

            self.tenantscope = TenantScopeConfig.from_any(self.tenantscope)
        if self.telemetry is not None:
            from ..observability.server import TelemetryConfig

            self.telemetry = TelemetryConfig.from_any(self.telemetry)
        if self.autoscale is not None:
            from ..serving.autoscaler import AutoscaleConfig

            self.autoscale = AutoscaleConfig.from_any(self.autoscale)

    @classmethod
    def from_any(cls, cfg: "ServingConfig | dict | None") -> "ServingConfig":
        if cfg is None:
            return cls()
        if isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown serving config keys: {sorted(unknown)}")
        return cls(**cfg)


@dataclasses.dataclass
class InferenceConfig:
    dtype: str = "bfloat16"            # compute dtype for decode
    tensor_parallel: int = 1           # reference tensor_parallel.tp_size
    expert_parallel: int = 1           # reference moe.ep_size: experts served
                                       # sharded over the mesh 'expert' axis
    max_out_tokens: int = 256          # reference max_out_tokens
    quantize: bool = False             # weight-only quant (WOQ)
    quant_group_size: int = 128
    quant_bits: int = 8                # 8 or 4 (nibble-packed)
    eos_token_id: Optional[int] = None
    seed: int = 0
    # Pallas streaming cache-attention for the 1-token decode step
    # (ops/decode_attention.py). None = auto: on for TPU, off elsewhere
    # (interpret-mode Pallas inside the decode scan is test-only slow).
    flash_decode: Optional[bool] = None
    # WOQ only: route eligible quantized projections through the fused
    # Pallas dequant-in-VMEM GEMM (ops/woq_matmul.py) so decode reads
    # int8/int4 bytes from HBM by construction. None = auto: on for TPU,
    # off elsewhere (the XLA per-use dequant is the portable fallback).
    woq_kernel: Optional[bool] = None
    # Subsumed knob, accepted for config compat: decode now keeps weights
    # quantized end-to-end and dispatches the dequant at each consumption
    # site, so there is no hoisted whole-tree dequant to toggle anymore
    # (round-5 WOQ_PROBE showed XLA hoisting it either way).
    dequant_per_step: bool = False
    # Request tracing (observability/tracing.py): every generate() records
    # TTFT, per-token decode latency, tokens/s, and roofline MBU into a
    # ring buffer surfaced by InferenceEngine.metrics_snapshot(). When on,
    # generation compiles as two programs (prefill / decode scan) and pays
    # ONE extra host sync per request — never one per token. When off
    # (default), generate() keeps the single fused program and adds no
    # host synchronization at all.
    observability: bool = False
    trace_ring_size: int = 256
    # Quantized TP decode collective (EQuARX-style two-sided int8): spell
    # the T=1 decode step's model-axis partial-sum reductions — the
    # attention output (wo) and dense-MLP output (w_out) row-sharded
    # matmuls — as explicit blockwise-int8 all-reduces (both hops int8 +
    # fp32 block scales, comm/compressed.py int8_psum) instead of the
    # fp psum GSPMD inserts. ~4x fewer wire bytes per decode step on the
    # dominant TP collectives; greedy short-context decode stays exactly
    # token-parity with the fp default (the serving tests' oracle). 0
    # (default) keeps the GSPMD fp psum — bit-frozen, zero new programs;
    # TP=1 meshes are a no-op either way. Logits (the sampler's input)
    # are never quantized.
    tp_comm_quant: int = 0             # 0 = off, 8 = int8
    # Decode in host-checked chunks of this many steps instead of one fused
    # scan: between chunks the engine reads the (B,) done flags and stops
    # as soon as every row hit eos, so a batch that finishes early stops
    # paying for the dead tail of max_new_tokens. 0 (default) keeps the
    # zero-sync fused path; the chunked path costs one host sync per chunk
    # and is bit-identical (the tail is eos-filled either way).
    decode_chunk: int = 0
    # Continuous-batching knobs for serving.ServingEngine (ignored by the
    # plain generate() path). Accepts a nested dict in from_any.
    serving: "ServingConfig | None" = None

    def flash_decode_resolved(self) -> bool:
        if self.flash_decode is not None:
            return self.flash_decode
        import jax

        return jax.default_backend() == "tpu"

    def woq_kernel_resolved(self) -> bool:
        if self.woq_kernel is not None:
            return self.woq_kernel
        import jax

        return jax.default_backend() == "tpu"

    @classmethod
    def from_any(cls, cfg: "InferenceConfig | dict | None") -> "InferenceConfig":
        if cfg is None:
            return cls()
        if isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        flat = dict(cfg)
        # accept the reference's nested {"tensor_parallel": {"tp_size": N}}
        tp = flat.get("tensor_parallel")
        if isinstance(tp, dict):
            flat["tensor_parallel"] = int(tp.get("tp_size", 1))
        # accept the reference's {"moe": {"ep_size": N}} nesting — with the
        # same strictness as top-level keys (a typo'd sub-key must raise,
        # not silently serve with expert_parallel=1)
        moe = flat.pop("moe", None)
        if moe is not None:
            if not isinstance(moe, dict):
                raise ValueError("inference config 'moe' must be a dict "
                                 f"like {{'ep_size': N}}, got {moe!r}")
            unknown_moe = set(moe) - {"ep_size"}
            if unknown_moe:
                raise ValueError(f"unknown moe config keys: {sorted(unknown_moe)}")
            flat.setdefault("expert_parallel", int(moe.get("ep_size", 1)))
        srv = flat.get("serving")
        if srv is not None:
            flat["serving"] = ServingConfig.from_any(srv)
        unknown = set(flat) - known
        if unknown:
            raise ValueError(f"unknown inference config keys: {sorted(unknown)}")
        return cls(**flat)

    @property
    def compute_dtype(self) -> Any:
        return _DTYPES[self.dtype]
