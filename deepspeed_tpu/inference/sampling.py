"""Token sampling: greedy, temperature, top-k, top-p.

Reference analog: generation policy handled by HF ``generate`` on top of the
reference engine; here sampling is jit-compiled alongside the decode step.
All samplers are static-shape (top-k via ``lax.top_k``, top-p via sorted
cumulative mass) so the whole generation loop stays one compiled program.

RNG comes in two layouts, chosen by the caller's key shape:
- one (2,) key: a single sampling stream for the whole batch (the
  classic ``generate()`` contract — batch composition changes the draws);
- a (B, 2) per-row key stack: every row draws from its OWN stream. A row
  keyed from its request seed then samples identically whether it runs
  alone, in a static batch, or through the serving scheduler — the
  property the continuous-batching parity tests pin down.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def split_keys(rng):
    """``jax.random.split`` that also accepts a (B, 2) per-row key stack —
    each row splits its own chain, independent of every other row (and of
    how many rows the batch happens to hold)."""
    if rng.ndim == 2:
        ks = jax.vmap(jax.random.split)(rng)        # (B, 2, 2)
        return ks[:, 0], ks[:, 1]
    return jax.random.split(rng)


def per_request_keys(seeds) -> jnp.ndarray:
    """(B,) request seeds → (B, 2) per-row key stack (host-side helper).

    Keys are folded from the request SEED, never from the row index, so a
    request's sampling stream is invariant to where it lands in a batch
    or which serving slot it occupies."""
    return jnp.stack([jax.random.PRNGKey(int(s)) for s in seeds])


def sample_logits(logits, rng, *, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 1.0, greedy: bool = False):
    """logits: (B, V) → (B,) int32 token ids. ``rng``: one (2,) key or a
    (B, 2) per-row stack (each row then draws from its own stream)."""
    if greedy or temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / jnp.float32(max(temperature, 1e-6))
    if top_k and top_k > 0:
        kth = lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set of tokens with cumulative mass >= top_p
        cutoff_idx = jnp.sum((cum - probs) < top_p, axis=-1) - 1
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    if rng.ndim == 2:
        # Per-row draws sample over fully REPLICATED logits: the vmapped
        # per-key gumbel-argmax composes badly with vocab-'model'-sharded
        # logits under GSPMD (each shard's correct index gets summed by a
        # spurious cross-shard reduce — token id x tp_size garbage). A
        # (B, V) gather at the sample point is noise next to a decode
        # step, and the constraint is a no-op off-mesh, so single-chip
        # draws are unchanged bit-for-bit. The single-key path below keeps
        # its original sharded lowering (correct since PR 0, TP-tested).
        from jax.sharding import PartitionSpec as P

        from ..platform.mesh import constrain

        logits = constrain(logits, P())
        return jax.vmap(jax.random.categorical)(rng, logits).astype(jnp.int32)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
