"""Token sampling: greedy, temperature, top-k, top-p.

Reference analog: generation policy handled by HF ``generate`` on top of the
reference engine; here sampling is jit-compiled alongside the decode step.
All samplers are static-shape (top-k via ``lax.top_k``, top-p via sorted
cumulative mass) so the whole generation loop stays one compiled program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def sample_logits(logits, rng, *, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 1.0, greedy: bool = False):
    """logits: (B, V) → (B,) int32 token ids."""
    if greedy or temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / jnp.float32(max(temperature, 1e-6))
    if top_k and top_k > 0:
        kth = lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set of tokens with cumulative mass >= top_p
        cutoff_idx = jnp.sum((cum - probs) < top_p, axis=-1) - 1
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
