"""InferenceEngine: TP-sharded, jit-compiled generation.

Reference: ``deepspeed/inference/engine.py:39`` — wraps the model, builds the
TP group, converts dtype, injects kernels, captures CUDA graphs, and serves
``generate``. Here: params are device_put against the model's sharding specs
over a ``model``-axis mesh (TP == AutoTP without the module-graph walking,
since the sharding rules ARE the policy), the decode loop is one jitted
``lax.scan`` over a static KV cache (graph capture subsumed by XLA), the
serving tree fuses the attention projections into one column-sharded
[wq|wk|wv] GEMM, and int8/int4 WOQ keeps weights quantized END-TO-END —
the decode step consumes them through the fused dequant-in-VMEM Pallas
GEMM (ops/woq_matmul.py), so each token re-reads int8 bytes from HBM, not
a hoisted bf16 copy (docs/WOQ_DECODE.md).
"""

from __future__ import annotations

import copy
import dataclasses
from collections import OrderedDict
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..platform.mesh import MeshSpec, build_mesh
from ..utils.logging import log_dist
from .config import InferenceConfig
from .decode import decode_tokens, generate_tokens, prefill_tokens
from .quantization import (dequantize_params, quantize_params,
                           quantized_bytes, quantized_shardings)
from .sampling import per_request_keys, sample_logits

# Compiled generate programs kept per engine (each pins an executable).
_MAX_COMPILED_SHAPES = 32


def model_with_dtype(model, dtype):
    """Shallow-clone a model so its config compute dtype matches ``dtype``
    (the model reads ``cfg.dtype`` for every cast — the engine's dtype knob
    must actually reach it)."""
    if model.cfg.dtype == dtype:
        return model
    clone = copy.copy(model)
    clone.cfg = dataclasses.replace(model.cfg, dtype=dtype)
    return clone


class InferenceEngine:
    """Owns sharded params + compiled prefill/decode/generate."""

    def __init__(self, model, params, config: InferenceConfig | dict | None = None,
                 mesh: Optional[Mesh] = None):
        self.config = InferenceConfig.from_any(config)
        cfg = self.config
        if cfg.dequant_per_step:
            from ..utils.logging import warning_once

            warning_once(
                "inference config: dequant_per_step is obsolete — decode "
                "now keeps weights quantized end-to-end and dequantizes "
                "at each consumption site (the fused WOQ GEMM); the knob "
                "is accepted for config compat but changes nothing.")
        self.compute_dtype = cfg.compute_dtype
        self.model = model_with_dtype(model, self.compute_dtype)
        if getattr(self.model.cfg, "num_experts", 1) > 1:
            # MoE prefill routes through the training dispatch; serve with
            # the (larger) eval capacity factor so fewer tokens drop
            # (reference eval_capacity_factor). Clone before flagging so a
            # shared training model doesn't inherit eval routing.
            if self.model is model:
                self.model = copy.copy(model)
            self.model.moe_eval_mode = True
        num_experts = int(getattr(self.model.cfg, "num_experts", 1) or 1)
        if cfg.expert_parallel > 1:
            # reference expert-parallel serving (moe_inference.py:159 builds
            # the ep group); here the serving mesh carries an 'expert' axis
            # and the MoE dispatch's sharding constraints do the all-to-all
            if num_experts % cfg.expert_parallel != 0:
                raise ValueError(
                    f"expert_parallel={cfg.expert_parallel} must divide "
                    f"num_experts={num_experts} (dense models serve with "
                    "expert_parallel=1)")
        self.mesh = mesh or build_mesh(MeshSpec(
            data=-1, expert=cfg.expert_parallel, model=cfg.tensor_parallel))

        # Same fp32 exemptions as the training engine's compute cast
        # (runtime/engine.py _cast_compute): leaves the model names — MoE
        # routers above all — stay fp32 so near-tie routing decisions
        # don't flap across bf16 rounding at serve time.
        keep = set(getattr(self.model, "fp32_param_names", lambda: ())())

        def _cast(path, p):
            name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
            if name in keep or not jnp.issubdtype(p.dtype, jnp.floating):
                return p
            return p.astype(self.compute_dtype)

        cast = jax.tree_util.tree_map_with_path(_cast, params)
        specs = self.model.param_specs()
        # Fuse the attention projections into one [wq | wk | wv] weight
        # for the serving tree: the decode step runs ONE batched GEMM over
        # the shared post-norm activations instead of three skinny ones
        # (reference qkv_gemm fusion, csrc/transformer/inference). The
        # column-concat keeps Megatron column sharding: spec stays
        # (None, None, "model").
        self._fused = self._can_fuse_qkv(cast)
        if self._fused:
            cast = self._fuse_qkv_params(cast)
            specs = self._fuse_qkv_specs(specs)
        if cfg.quantize:
            # WOQ x TP: quantize straight into the sharded layout — the
            # shardings for the quantized tree come from the same
            # param_specs the dense path uses (scales follow their weights;
            # quantized_shardings docs), and each leaf's spec travels in
            # its aux data so the decode-side kernel dispatch can
            # shard_map accordingly. eval_shape first so nothing is ever
            # materialized unsharded.
            quant = partial(quantize_params, group_size=cfg.quant_group_size,
                            bits=cfg.quant_bits, specs=specs)
            q_shapes = jax.eval_shape(quant, cast)
            shardings = quantized_shardings(specs, q_shapes, self.mesh)
            with self.mesh:
                self.params = jax.jit(quant, out_shardings=shardings)(cast)
            # the decode consumption sites read this flag off the model
            # (shared code paths can't thread an engine handle through);
            # clone first so a shared training model isn't flagged
            if self.model is model:
                self.model = copy.copy(model)
            self.model.woq_kernel = cfg.woq_kernel_resolved()
            log_dist(f"inference: int{cfg.quant_bits} WOQ, "
                     f"{quantized_bytes(self.params)/2**20:.0f}"
                     f" MiB weights, tp={cfg.tensor_parallel}, "
                     f"kernel={self.model.woq_kernel}", ranks=[0])
        else:
            shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s if s is not None else P()),
                specs, is_leaf=lambda x: x is None or isinstance(x, P))
            self.params = jax.device_put(cast, shardings)
        if cfg.decode_chunk < 0:
            raise ValueError(f"decode_chunk must be >= 0, got "
                             f"{cfg.decode_chunk}")
        if cfg.tp_comm_quant not in (0, 8):
            raise ValueError(f"tp_comm_quant must be 0 (off) or 8 (int8), "
                             f"got {cfg.tp_comm_quant}")
        if cfg.tp_comm_quant:
            # stamped on the model like woq_kernel: the shared decode step
            # can't thread an engine handle through. Clone first so a
            # shared training model isn't flagged.
            if self.model is model:
                self.model = copy.copy(model)
            self.model.tp_quant = cfg.tp_comm_quant
            log_dist(f"inference: int{cfg.tp_comm_quant} quantized TP "
                     f"decode collective (tp={cfg.tensor_parallel}; "
                     "wo/w_out psums two-sided int8, logits stay fp)",
                     ranks=[0])
        self._gen_cache: OrderedDict = OrderedDict()
        # split prefill/decode program caches: used by request tracing AND
        # by the chunked-decode early-stop path (decode_chunk > 0)
        self._prefill_cache: OrderedDict = OrderedDict()
        self._decode_cache: OrderedDict = OrderedDict()
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._fwd = jax.jit(self._forward_impl)
        # Request tracing (observability): ring buffer + Serve/* registry.
        # Built lazily-enough that the disabled path allocates nothing and
        # generate() stays on the single fused program with zero added
        # host syncs.
        self.tracer = None
        if cfg.observability:
            from ..observability.tracing import RequestTracer
            from ..utils.timer import peak_hbm_bw_for
            from .quantization import decode_weight_bytes

            try:
                peak_bw = peak_hbm_bw_for(jax.devices()[0])
            except ValueError as e:
                # Unknown hardware must not break serving — latencies still
                # trace; only the MBU attribution goes dark.
                log_dist(f"inference observability: MBU disabled ({e})",
                         ranks=[0])
                peak_bw = None
            self.tracer = RequestTracer(
                ring_size=cfg.trace_ring_size,
                bytes_per_step=decode_weight_bytes(self.params),
                peak_bw=peak_bw)

    # ------------------------------------------------------------ qkv fuse
    def _can_fuse_qkv(self, params) -> bool:
        """Only decoder trunks that generate get the fused serving layout
        (the training ``apply`` path reads per-projection names; encoder /
        feature towers only ever run ``forward``, which would pay the
        unfuse slicing for nothing)."""
        layers = params.get("layers") if isinstance(params, dict) else None
        return (getattr(self.model.cfg, "objective", None) == "clm"
                and isinstance(layers, dict)
                and all(k in layers for k in ("wq", "wk", "wv")))

    def _fuse_qkv_params(self, params):
        layers = dict(params["layers"])
        layers["wqkv"] = jnp.concatenate(
            [layers.pop("wq"), layers.pop("wk"), layers.pop("wv")], axis=-1)
        if all(k in layers for k in ("bq", "bk", "bv")):
            layers["bqkv"] = jnp.concatenate(
                [layers.pop("bq"), layers.pop("bk"), layers.pop("bv")],
                axis=-1)
        return {**params, "layers": layers}

    def _fuse_qkv_specs(self, specs):
        layers = dict(specs["layers"])
        for k in ("wq", "wk", "wv"):
            layers.pop(k, None)
        layers["wqkv"] = P(None, None, "model")
        if "bq" in layers:
            for k in ("bq", "bk", "bv"):
                layers.pop(k, None)
            layers["bqkv"] = P(None, "model")
        return {**specs, "layers": layers}

    def _unfused(self, params):
        """Split the serving tree's fused qkv back into per-projection
        leaves (XLA slices; only the cold ``forward`` path pays this)."""
        if not self._fused:
            return params
        cfg = self.model.cfg
        qd = cfg.n_head * cfg.head_dim
        kvd = cfg.kv_heads * cfg.head_dim
        layers = dict(params["layers"])
        w = layers.pop("wqkv")
        layers["wq"], layers["wk"], layers["wv"] = (
            w[..., :qd], w[..., qd:qd + kvd], w[..., qd + kvd:])
        if "bqkv" in layers:
            b = layers.pop("bqkv")
            layers["bq"], layers["bk"], layers["bv"] = (
                b[..., :qd], b[..., qd:qd + kvd], b[..., qd + kvd:])
        return {**params, "layers": layers}

    # -------------------------------------------------------------- forward
    def _materialized(self, params):
        if self.config.quantize:
            return dequantize_params(params, self.compute_dtype)
        return params

    def _forward_impl(self, params, input_ids):
        return self.model.apply(self._unfused(self._materialized(params)),
                                input_ids)

    def forward(self, input_ids) -> jnp.ndarray:
        """Full forward (no cache): (B, S) → (B, S, V) logits."""
        with self.mesh:
            return self._fwd(self.params, jnp.asarray(input_ids))

    __call__ = forward

    # ------------------------------------------------------------- generate
    def _generate_impl(self, params, input_ids, rng, *, max_new: int,
                       temperature: float, top_k: int, top_p: float,
                       greedy: bool, cache_len=None):
        # Quantized trees stay int8/int4 through the whole decode scan —
        # the step's consumption sites dispatch per-use (generate_tokens
        # docs). Only the prefill materializes (compute-bound; dense is
        # right there). ``dequant_per_step`` is subsumed: decode never
        # re-reads a dequantized copy anymore.
        return generate_tokens(
            self.model, params,
            input_ids, rng, max_new=max_new,
            sampler=self._sampler(temperature, top_k, top_p, greedy),
            eos_token_id=self.config.eos_token_id,
            cache_dtype=self.compute_dtype,
            flash_decode=self.config.flash_decode_resolved(),
            materialize=self._materialized if self.config.quantize else None,
            cache_len=cache_len)

    def _sampler(self, temperature: float, top_k: int, top_p: float,
                 greedy: bool):
        return partial(sample_logits, temperature=temperature, top_k=top_k,
                       top_p=top_p, greedy=greedy)

    def _prefill_impl(self, params, input_ids, rng, *, max_new: int,
                      temperature: float, top_k: int, top_p: float,
                      greedy: bool, cache_len=None):
        return prefill_tokens(
            self.model, params, input_ids, rng, max_new=max_new,
            sampler=self._sampler(temperature, top_k, top_p, greedy),
            eos_token_id=self.config.eos_token_id,
            cache_dtype=self.compute_dtype,
            flash_decode=self.config.flash_decode_resolved(),
            materialize=self._materialized if self.config.quantize else None,
            cache_len=cache_len)

    def _decode_impl(self, params, carry, *, steps: int, temperature: float,
                     top_k: int, top_p: float, greedy: bool,
                     return_carry: bool = False):
        return decode_tokens(
            self.model, params, carry, steps=steps,
            sampler=self._sampler(temperature, top_k, top_p, greedy),
            eos_token_id=self.config.eos_token_id,
            flash_decode=self.config.flash_decode_resolved(),
            return_carry=return_carry)

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def generate(self, input_ids, max_new_tokens: Optional[int] = None, *,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 greedy: bool = False, rng: Optional[jax.Array] = None,
                 request_seeds=None, cache_len: Optional[int] = None):
        """(B, S) prompt ids → (B, max_new_tokens) continuations.

        Sampled calls draw from the engine's persistent PRNG stream (pass
        ``rng`` explicitly for reproducibility). ``request_seeds`` — one
        int per row — switches to per-request sampling streams instead:
        each row's draws are folded from its own seed, so the same request
        reproduces bit-identically whether served alone, in any static
        batch, or through the continuous-batching scheduler
        (``serving.ServingEngine`` uses the same per-row chains).
        ``cache_len`` overrides the tight ``S + max_new`` KV allocation —
        bucket it to serve many shapes from one compiled program, and pin
        it to the serving engine's ``max_len`` to reproduce a served
        request exactly (cache width is part of the sampled bit-stream).
        One program is compiled per (shape, knobs) tuple and kept in a
        bounded LRU.
        """
        # Non-CLM guard lives in generate_tokens (shared with HybridEngine);
        # re-check here so the error surfaces before a jit trace is built.
        objective = getattr(getattr(self.model, "cfg", None), "objective", "clm")
        if objective != "clm":
            raise ValueError(
                f"generate() needs a causal LM head; this model's objective "
                f"is {objective!r} — use forward() (MLM logits / feature "
                "hidden states) instead")
        input_ids = jnp.asarray(input_ids, jnp.int32)
        max_new = int(max_new_tokens or self.config.max_out_tokens)
        if request_seeds is not None:
            if rng is not None:
                raise ValueError("pass either rng or request_seeds, not both")
            if len(request_seeds) != input_ids.shape[0]:
                raise ValueError(
                    f"request_seeds has {len(request_seeds)} entries for a "
                    f"batch of {input_ids.shape[0]}")
            rng = per_request_keys(request_seeds)
        rng = rng if rng is not None else self._next_rng()
        if cache_len is not None:
            cache_len = int(cache_len)
        # rng shape is part of the program signature: a (B, 2) per-row key
        # stack samples through vmapped draws, a (2,) key through one
        key = (input_ids.shape, tuple(rng.shape), max_new, cache_len,
               float(temperature), int(top_k), float(top_p), bool(greedy))
        knobs = dict(temperature=temperature, top_k=top_k, top_p=top_p,
                     greedy=greedy)
        if self.config.decode_chunk > 0:
            return self._chunked_generate(input_ids, rng, key, max_new,
                                          knobs, cache_len)
        if self.tracer is not None:
            return self._traced_generate(input_ids, rng, key, max_new,
                                         knobs, cache_len)
        # Fast path: ONE fused prefill+decode program, nothing read back to
        # the host until the caller consumes the tokens — tracing disabled
        # means zero added synchronization.
        fn = self._cached(self._gen_cache, key, lambda: jax.jit(
            partial(self._generate_impl, max_new=max_new,
                    cache_len=cache_len, **knobs)))
        with self.mesh:
            return fn(self.params, input_ids, rng)

    @staticmethod
    def _cached(cache: OrderedDict, key, build, cap: int = _MAX_COMPILED_SHAPES):
        """Get-or-build with the engine's bounded-LRU policy (ONE policy:
        the fused / prefill / decode caches here and the serving engine's
        program cache all go through this)."""
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = build()
            if len(cache) > cap:
                cache.popitem(last=False)
        else:
            cache.move_to_end(key)
        return fn

    def _traced_generate(self, input_ids, rng, key, max_new: int,
                         knobs: dict, cache_len=None):
        """Request-traced generation: prefill and decode as two compiled
        programs so their wall times are separable (TTFT vs per-token
        decode). Costs one host sync between the phases; tokens match the
        fused path bit-for-bit (same sampler chain, same rng splits)."""
        B, S = input_ids.shape
        cold = key not in self._prefill_cache
        pf = self._cached(self._prefill_cache, key, lambda: jax.jit(
            partial(self._prefill_impl, max_new=max_new,
                    cache_len=cache_len, **knobs)))
        # The carry (KV cache above all) is dead after the decode call:
        # donate it so the scan reuses the prefill cache buffers in place —
        # matching the fused path, where the cache lives in the scan carry
        # and is never copied. Without donation each traced request would
        # hold two full caches and pay a copy the tracer then mis-attributes
        # to decode time.
        dc = self._cached(self._decode_cache, key, lambda: jax.jit(
            partial(self._decode_impl, steps=max_new - 1, **knobs),
            donate_argnums=(1,)))
        clock = self.tracer.clock
        t0 = clock()
        with self.mesh:
            carry = pf(self.params, input_ids, rng)
            jax.block_until_ready(carry)
            t1 = clock()
            out = dc(self.params, carry)
            jax.block_until_ready(out)
        t2 = clock()
        self.tracer.observe(batch=B, prompt_len=S, new_tokens=max_new,
                            prefill_s=t1 - t0, decode_s=t2 - t1, cold=cold)
        return out

    def _chunked_generate(self, input_ids, rng, key, max_new: int,
                          knobs: dict, cache_len=None):
        """Decode in ``decode_chunk``-step chunks with a host-side
        ``done.all()`` check between chunks: a batch where every row hit
        eos stops paying for the dead tail of max_new_tokens. Costs one
        host sync per chunk; tokens are bit-identical to the fused path
        (post-eos rows emit eos there too, and the early-stopped tail is
        eos-filled here)."""
        import numpy as np

        chunk = int(self.config.decode_chunk)
        eos = self.config.eos_token_id
        B, S = input_ids.shape
        cold = key not in self._prefill_cache
        clock = self.tracer.clock if self.tracer is not None else None
        pf = self._cached(self._prefill_cache, key, lambda: jax.jit(
            partial(self._prefill_impl, max_new=max_new,
                    cache_len=cache_len, **knobs)))
        t0 = clock() if clock else 0.0
        parts = []
        with self.mesh:
            carry = pf(self.params, input_ids, rng)
            if clock:
                jax.block_until_ready(carry)
            t1 = clock() if clock else 0.0
            remaining = max_new - 1
            if remaining == 0:   # prefill's token is the whole output
                parts.append(np.asarray(carry.tok)[:, None])
            first = True
            while remaining > 0:
                steps = min(chunk, remaining)
                # a decode chunk program compiling MID-request (e.g. the
                # ragged final chunk of a budget an earlier early-stopped
                # request never reached) is a cold sample too — its compile
                # seconds must stay out of the latency reservoirs
                cold = cold or (key, steps) not in self._decode_cache
                # same donation contract as the traced path: the carry's
                # KV cache is dead after the call — reuse it in place
                dc = self._cached(
                    self._decode_cache, (key, steps), lambda: jax.jit(
                        partial(self._decode_impl, steps=steps,
                                return_carry=True, **knobs),
                        donate_argnums=(1,)))
                seg, carry = dc(self.params, carry)
                # chunk returns [carry_tok, d1..d_steps]; the carry token
                # is the previous chunk's last emitted column
                parts.append(np.asarray(seg if first else seg[:, 1:]))
                first = False
                remaining -= steps
                if remaining > 0 and eos is not None \
                        and bool(np.asarray(carry.done).all()):
                    parts.append(np.full((B, remaining), eos, np.int32))
                    break
        out = jnp.asarray(np.concatenate(parts, axis=1))
        if self.tracer is not None:
            t2 = clock()
            self.tracer.observe(batch=B, prompt_len=S, new_tokens=max_new,
                                prefill_s=t1 - t0, decode_s=t2 - t1,
                                cold=cold)
        return out

    def metrics_snapshot(self) -> dict:
        """Serving metrics: request count, TTFT / per-token-latency
        percentiles, tokens/s, achieved weight-GB/s and decode MBU, plus
        the most recent request records. ``{"tracing": False}`` when the
        engine was built without ``observability`` (the zero-sync path
        records nothing)."""
        if self.tracer is None:
            return {"tracing": False, "requests": 0}
        return {"tracing": True, **self.tracer.snapshot()}

    def publish_metrics(self, monitor, step: Optional[int] = None) -> int:
        """Push the ``Serve/*`` registry through a monitor fan-out — a
        :class:`~deepspeed_tpu.monitor.monitor.MonitorMaster` or anything
        with ``write_events([(name, value, step)])``.

        Unlike the training engine (whose step loop flushes its sinks at
        report boundaries), serving has no universal cadence — the
        serving loop owns it: call this from a timer or every N requests.
        ``step`` defaults to the request count. Returns the number of
        events written (0 when tracing is off)."""
        if self.tracer is None:
            return 0
        from ..observability.metrics import publish_registry

        return publish_registry(self.tracer.registry, monitor, step,
                                default_step_counter="Serve/requests")


def init_inference(model, params=None, config: InferenceConfig | dict | None = None,
                   mesh: Optional[Mesh] = None, **kwargs) -> InferenceEngine:
    """Public entry point (reference ``deepspeed.init_inference``,
    ``deepspeed/__init__.py:269``)."""
    if params is None:
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
    return InferenceEngine(model, params, config, mesh=mesh, **kwargs)
