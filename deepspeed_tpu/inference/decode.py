"""Prefill + single-token decode with a static-shape KV cache.

Reference analog: the fused inference kernels and KV-cache workspace of
``csrc/transformer/inference/`` (``softmax_context`` = attention over the
cache, ``inference_context.h`` = the cache allocator). TPU-native: the cache
is a pair of ``(L, B, KV, max_len, hd)`` arrays updated with
``dynamic_update_slice`` inside the compiled step; attention over the cache
masks positions beyond the current length, so every decode step has an
identical static shape (one compiled program for the whole generation).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models.transformer import TransformerConfig, _activation, _norm, _rope
from ..platform.mesh import BATCH_AXES, constrain
from .quantization import (QuantizedTensor, dequant_rows, matmul_any,
                           tp_quant_dot, woq_dot, woq_dot_t)

# Host constant, NOT jnp.float32(...): a device constant here would run a
# computation at import time and initialize the XLA backend — which breaks
# multi-host jobs that must call jax.distributed.initialize() first.
BIG_NEG = -2.0 ** 30


class KVCache(NamedTuple):
    # (L, B, KV, max_len, hd): heads-major so the Pallas decode kernel's
    # cache operand blocks as (None, None, max_len, hd) — TPU lowering
    # requires the last two block dims be (sublane, lane)-shaped, which a
    # seq-major (max_len, KV, hd) layout cannot satisfy (round-5 hardware
    # contact: "block shape ... (Squeezed(), Blocked(256), Squeezed(), 64)")
    k: jnp.ndarray           # (L, B, KV, max_len, hd)
    v: jnp.ndarray           # (L, B, KV, max_len, hd)
    length: jnp.ndarray      # i32 tokens cached: scalar (all rows advance
                             # together) or (B,) per-slot (serving/slots.py)


class PagedKVCache(NamedTuple):
    """Page-pool KV state for the serving slot batch (serving/pages.py).

    The contiguous per-slot cache above owns ``max_len`` positions per
    slot whether or not they are ever written; the paged layout instead
    pools fixed-size pages shared by all slots, and each slot maps its
    logical positions onto pool pages through an integer ``page_table``
    row. Identical prompt prefixes can then point at the SAME physical
    pages (host-side radix tree, refcounted) — prefilled once, shared
    copy-free. Pool page 0 is a reserved scratch page: idle slots' table
    rows (and the shared-page entries of an insert) are redirected there,
    so a retired or not-yet-placed row's appends can never touch live
    data.

    ``k``/``v`` are the pools in the compute dtype, or int8 when the KV
    cache itself is quantized (``kv_quant_bits=8``); then ``k_scale`` /
    ``v_scale`` hold symmetric per-token per-head scales alongside the
    pages (``None`` in fp mode), quantized on append and dequantized at
    the attention read — the same point-of-use dispatch discipline as the
    WOQ weight path (never a hoisted dequantized copy of the pool)."""

    k: jnp.ndarray            # (L, pages, KV, page_size, hd) fp or int8
    v: jnp.ndarray            # (L, pages, KV, page_size, hd) fp or int8
    k_scale: "jnp.ndarray | None"   # (L, pages, KV, page_size) f32 | None
    v_scale: "jnp.ndarray | None"   # (L, pages, KV, page_size) f32 | None
    page_table: jnp.ndarray   # (slots, pages_per_slot) i32 pool page ids
    length: jnp.ndarray       # (slots,) i32 tokens cached per slot

    @property
    def page_size(self) -> int:
        return self.k.shape[3]


def cache_layout(cfg: TransformerConfig, batch: int, max_len: int,
                 dtype=None, *, page_size: int = 0, pages: int = 0) -> tuple:
    """(shape, dtype) of one K or V cache buffer — the single source of
    truth shared by :func:`init_cache`, the serving slot allocator
    (``serving/slots.py``), and the paged pool allocator
    (``serving/pages.py``), so a prefilled request's cache can be written
    into its slot (or scattered into its pages) with no relayout.

    ``page_size=0`` (default) is the contiguous per-slot layout
    ``(L, batch, KV, max_len, hd)``; ``page_size > 0`` is the pooled page
    layout ``(L, pages, KV, page_size, hd)`` — same trailing
    (sublane, lane) = (positions, hd) shape per page, so one page is a
    position-contiguous tile of the contiguous layout and the gather over
    a slot's page-table row reassembles exactly the contiguous view."""
    if page_size > 0:
        return ((cfg.n_layer, pages, cfg.kv_heads, page_size, cfg.head_dim),
                dtype or cfg.dtype)
    return ((cfg.n_layer, batch, cfg.kv_heads, max_len, cfg.head_dim),
            dtype or cfg.dtype)


def init_cache(cfg: TransformerConfig, batch: int, max_len: int,
               dtype=None) -> KVCache:
    shape, dtype = cache_layout(cfg, batch, max_len, dtype)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   length=jnp.zeros((), jnp.int32))


def _cache_attend(q, ck, cv, length, flash_decode: bool = False, bias=None,
                  alibi=None):
    """q: (B, T, H, hd) vs cache (B, KV, max_len, hd); positions >= length
    masked. For prefill T = prompt len (with causal offset); decode T = 1.

    ``length`` is a scalar (all rows at the same position — the
    single-request generate() path) or a (B,) vector of per-row lengths
    (the serving slot batch, where every slot is at its own position).
    The per-row math is the same expressions with a batch dim on the
    position grid; masked scores underflow to exactly 0 after softmax, so
    a row's output depends only on its own live positions.

    ``bias`` is an additive (H, T, max_len) score bias; ``alibi`` is the
    (H,) slope vector — preferred over a materialized bias because the
    streaming kernel reconstructs the distance ramp in-kernel, so Bloom
    decode stays on the fused path. ``flash_decode`` routes the T == 1
    hot path to the Pallas streaming kernel (ops/decode_attention.py)
    instead of materializing the full (B, H, 1, max_len) score tensor."""
    B, T, H, hd = q.shape
    # Mosaic has no f16: an fp16 engine (or an externally-built fp16 KV
    # cache under a bf16 trunk) must take the dense path on TPU instead of
    # failing Mosaic compilation inside the decode scan — same gate and
    # one-shot warning as flash_attention's.
    f16_in = any(jnp.dtype(x.dtype) == jnp.float16 for x in (q, ck, cv)) \
        and jax.default_backend() == "tpu"
    if f16_in and flash_decode:
        from ..utils.logging import warning_once

        warning_once(
            "decode: float16 q/KV-cache falls back to the dense XLA "
            "cache attention on TPU (Mosaic has no f16). The dense "
            "path materializes (B, H, 1, max_len) scores per step — "
            "prefer bf16 compute for long generations.")
    # TPU lane tiling wants full 128-wide blocks: generate_tokens pads the
    # cache to a 128 multiple when flash_decode is on, so this gate only
    # declines externally-built odd caches (which take the dense path
    # rather than risking an unaligned Pallas tile on hardware).
    if (flash_decode and not f16_in and bias is None and T == 1
            and ck.shape[2] % 128 == 0):
        from ..ops.decode_attention import decode_attention

        return decode_attention(q, ck, cv, length, alibi_slopes=alibi)
    KV = ck.shape[1]
    if KV != H:
        ck = jnp.repeat(ck, H // KV, axis=1)
        cv = jnp.repeat(cv, H // KV, axis=1)
    scores = jnp.einsum("bthd,bhsd->bhts", q, ck).astype(jnp.float32)
    scores = scores / math.sqrt(hd)
    if getattr(length, "ndim", 0) == 1:
        # per-slot lengths: the position grid gains a batch dim; an
        # externally materialized bias has no per-row layout, so only the
        # in-house alibi slopes are supported here
        if bias is not None:
            raise ValueError("per-slot lengths don't compose with a "
                             "materialized (H, T, max_len) bias — pass "
                             "alibi slopes instead")
        t_pos = length[:, None, None] - T \
            + jnp.arange(T)[None, :, None]               # (B, T, 1)
        s_pos = jnp.arange(ck.shape[2])[None, None, :]   # (1, 1, max_len)
        if alibi is not None:
            rel = (s_pos - t_pos).astype(jnp.float32)    # (B, T, max_len)
            scores = scores + alibi[None, :, None, None] * rel[:, None]
        keep = s_pos <= t_pos                            # (B, T, max_len)
        scores = jnp.where(keep[:, None], scores, BIG_NEG)
    else:
        # query t sits at global position length - T + t; key at slot s —
        # ONE set of position math drives both the alibi bias and the mask
        t_pos = length - T + jnp.arange(T)[:, None]      # (T, 1)
        s_pos = jnp.arange(ck.shape[2])[None, :]         # (1, max_len)
        if alibi is not None:
            rel = (s_pos - t_pos).astype(jnp.float32)    # (T, max_len)
            ab = alibi[:, None, None] * rel[None]        # (H, T, max_len)
            bias = ab if bias is None else bias + ab
        if bias is not None:
            scores = scores + bias[None]
        keep = s_pos <= t_pos                            # (T, max_len)
        scores = jnp.where(keep[None, None], scores, BIG_NEG)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bhsd->bthd", probs, cv)


def quantize_kv(x, axis: int = -1):
    """Symmetric int8 quantization of appended KV values: per-head scales
    (one fp32 scale per token per head over the ``hd`` axis), the KV-cache
    analog of the WOQ weight path's per-channel groups. ``quantize →
    dequantize → quantize`` is idempotent at these scales (the max
    element round-trips to exactly ±127), which is what lets a hydrated
    shared prefix re-insert without drift."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / jnp.expand_dims(scale, axis)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype, axis: int = -1):
    """Inverse of :func:`quantize_kv` at the point of use — the ONE
    spelling shared by the shared-prefix hydrate gather
    (``serving/pages.py``) and the host-tier restore scatter
    (``serving/hostkv.py``), so a page's bytes dequantize identically
    whether they come from the live pool or from pinned host memory."""
    return (q.astype(jnp.float32)
            * jnp.expand_dims(scale, axis)).astype(dtype)


def _paged_append(ck, cv, ks, vs, k, v, page_table, new_len):
    """Append T decode tokens' K/V per slot into the page pool.

    ``ck``/``cv`` are one layer's pools ``(pages, KV, page_size, hd)``;
    ``k``/``v`` the new projections ``(B, T, KV, hd)``; ``new_len`` the
    (B,) post-append lengths. Row ``b``'s token ``j`` writes position
    ``new_len[b] - T + j``, which maps through its ``page_table`` row to
    (pool page, in-page offset) — one scatter per pool covering all B·T
    writes. T == 1 is the plain decode step; T > 1 is the speculative
    verify forward (``serving/engine.py``), whose headroom gate
    guarantees every live row has ``new_len <= max_len`` so the clip
    below never folds a live write back onto the row's last page. Rows
    whose table entries are scratch (idle or freshly retired slots)
    write harmlessly into page 0; a live row past its last page clips
    onto scratch-redirected entries the host cleared at retirement, so
    stale rows can never touch another slot's pages."""
    B, T = k.shape[0], k.shape[1]
    ps, n = ck.shape[2], page_table.shape[1]
    pos = (new_len - T)[:, None] + jnp.arange(T, dtype=new_len.dtype)[None, :]
    pidx = jnp.clip(pos // ps, 0, n - 1)
    pid = jnp.take_along_axis(page_table, pidx, axis=1)     # (B, T)
    off = pos % ps
    if ks is not None:
        qk, sk = quantize_kv(k)
        qv, sv = quantize_kv(v)
        ck = ck.at[pid, :, off, :].set(qk)
        cv = cv.at[pid, :, off, :].set(qv)
        ks = ks.at[pid, :, off].set(sk)
        vs = vs.at[pid, :, off].set(sv)
    else:
        ck = ck.at[pid, :, off, :].set(k.astype(ck.dtype))
        cv = cv.at[pid, :, off, :].set(v.astype(cv.dtype))
    return ck, cv, ks, vs


def _paged_view(cp, sp, page_table, dtype):
    """Gather one layer's pool pages into the slot batch's contiguous
    attention view ``(B, KV, max_len, hd)`` — the page-table indirection
    the tentpole puts INSIDE the attention read. Page ids are data, not
    shapes: traffic churn changes table contents, never the program. An
    int8 pool dequantizes here, at the point of use (scales broadcast
    over ``hd``), so the fp path's gathered bytes are bit-identical to
    the contiguous cache and the int8 path never materializes a
    dequantized pool."""
    g = cp[page_table]                             # (B, n, KV, ps, hd)
    B, n, KV, ps, hd = g.shape
    g = g.transpose(0, 2, 1, 3, 4).reshape(B, KV, n * ps, hd)
    if sp is not None:
        s = sp[page_table].transpose(0, 2, 1, 3).reshape(B, KV, n * ps)
        g = (g.astype(jnp.float32) * s[..., None]).astype(dtype)
    return g


def _tp_quant_eligible(model, p, T: int) -> int:
    """int8 bits when the quantized TP decode collective applies to this
    step, else 0. Gates: the engine opted in (``tp_comm_quant``, stamped
    on the model like ``woq_kernel``), T == 1 (decode only — prefill is
    compute-bound and pays the psum once per request, not per token),
    and the row-sharded projections are DENSE (a WOQ ``QuantizedTensor``
    reduces inside its own shard_map — see ``woq_dot``'s psum — and
    keeps the fp wire there). ``tp_quant_dot`` itself declines meshes
    without a ``model`` axis, so a TP=1 engine with the knob on compiles
    the identical program."""
    bits = int(getattr(model, "tp_quant", 0) or 0)
    if not bits or T != 1:
        return 0
    if isinstance(p.get("wo"), QuantizedTensor):
        return 0
    return bits


def _mlp_tp_quant(model, y, p, bits: int):
    """The dense-MLP half of a decode step with the ``w_out`` model-axis
    partial-sum reduction quantized (two-sided int8) — the same math as
    ``TransformerLM._mlp_block`` (decode never remats, so the
    checkpoint-name tags there are identities this spelling drops).
    Falls back to the model's own block when the explicit spelling
    doesn't apply (no TP mesh, uneven shards, quantized w_out)."""
    cfg = model.cfg
    if isinstance(p.get("w_out"), QuantizedTensor):
        return model._mlp_block(y, p)
    u = model._maybe_bias(model._proj(y, p, "w_in"), p, "b_in")
    if cfg.is_glu:
        u = jax.nn.silu(model._proj(y, p, "w_gate")) * u
    else:
        u = _activation(u, cfg.activation)
    u = constrain(u, P(BATCH_AXES, "seq", "model"))
    out = tp_quant_dot(u, p["w_out"], bits=bits)
    if out is None:
        out = model._proj(u, p, "w_out")
    return model._maybe_bias(out, p, "b_out"), jnp.float32(0.0)


def _qkv_proj(model, y, p):
    """The attention projections as ONE GEMM when the engine pre-fused
    them (``wqkv`` = [wq | wk | wv] along the output dim, ``bqkv``
    likewise): a T=1 decode step's three skinny (B, d) x (d, n) dots
    become a single (B, d) x (d, 2d-ish) call — one weight stream, one
    MXU dispatch, one bias add — instead of three kernel launches over
    the same activations. Falls back to the per-projection weights for
    unfused trees (training params via HybridEngine, external callers)."""
    cfg = model.cfg
    B, T, _ = y.shape
    h, kv, hd = cfg.n_head, cfg.kv_heads, cfg.head_dim
    use_kernel = getattr(model, "woq_kernel", False)
    if "wqkv" in p:
        qkv = matmul_any(y, p["wqkv"], use_kernel=use_kernel)
        if cfg.use_bias and "bqkv" in p:
            qkv = qkv + p["bqkv"].astype(qkv.dtype)
        q, k, v = jnp.split(qkv, [h * hd, (h + kv) * hd], axis=-1)
    else:
        q = model._maybe_bias(matmul_any(y, p["wq"], use_kernel), p, "bq")
        k = model._maybe_bias(matmul_any(y, p["wk"], use_kernel), p, "bk")
        v = model._maybe_bias(matmul_any(y, p["wv"], use_kernel), p, "bv")
    return (q.reshape(B, T, h, hd), k.reshape(B, T, kv, hd),
            v.reshape(B, T, kv, hd))


@jax.named_scope("decode_layer")
def _layer_step(model, x, p, cache_k, cache_v, length, positions,
                flash_decode: bool = False, paged=None):
    """One transformer layer over x: (B, T, d), reading/writing the cache.

    Returns (x_out, new_cache_k, new_cache_v) — plus the new scale pools
    when ``paged`` is set. Mirrors ``TransformerLM._attention_block`` /
    ``_mlp_block`` with cache attention substituted for the full causal
    attention. Weights may arrive dense OR quantized (int8/int4
    ``QuantizedTensor`` leaves): every projection goes through the
    point-of-use dispatch, so quantized decode re-reads int8 bytes from
    HBM each step — never a hoisted bf16 copy.

    ``paged`` is ``(page_table, k_scale, v_scale)`` for the pooled page
    layout (serving decode: T == 1 plain steps, T == max_draft + 1
    speculative verify): the append scatters through the page table and
    the attention read gathers the slot's pages back into the contiguous
    view — same values, same mask math, so the fp paged step is
    bit-identical to the contiguous one by construction.
    """
    cfg = model.cfg
    B, T, d = x.shape
    h, kv, hd = cfg.n_head, cfg.kv_heads, cfg.head_dim

    y = _norm(x, p["ln1_scale"], p.get("ln1_bias"), cfg.norm, cfg.norm_eps)
    q, k, v = _qkv_proj(model, y, p)
    if cfg.pos_embedding == "rope":
        q, k = _rope(q, k, positions, cfg.rope_theta, cfg.rotary_dim)

    scale_k = scale_v = None
    if paged is not None:
        page_table, scale_k, scale_v = paged
        cache_k, cache_v, scale_k, scale_v = _paged_append(
            cache_k, cache_v, scale_k, scale_v, k, v, page_table, length)
        attend_k = _paged_view(cache_k, scale_k, page_table, cfg.dtype)
        attend_v = _paged_view(cache_v, scale_v, page_table, cfg.dtype)
    else:
        start = length - T  # cache slots [start, start+T) get the new k/v
        if getattr(length, "ndim", 0) == 1:
            # per-slot write positions: one dynamic_update_slice per row
            # via vmap (lowers to a scatter) — each serving slot appends
            # at its own length while the batch stays one static program
            upd = jax.vmap(lambda c, u, s: lax.dynamic_update_slice(
                c, u, (0, s, 0)))
            cache_k = upd(cache_k, k.swapaxes(1, 2).astype(cache_k.dtype),
                          start)
            cache_v = upd(cache_v, v.swapaxes(1, 2).astype(cache_v.dtype),
                          start)
        else:
            cache_k = lax.dynamic_update_slice(
                cache_k, k.swapaxes(1, 2).astype(cache_k.dtype),
                (0, 0, start, 0))
            cache_v = lax.dynamic_update_slice(
                cache_v, v.swapaxes(1, 2).astype(cache_v.dtype),
                (0, 0, start, 0))
        attend_k, attend_v = cache_k, cache_v
    alibi = None
    if cfg.pos_embedding == "alibi":
        # ALiBi positional signal (mirrors _attention_block's training
        # bias): passed as SLOPES — the streaming decode kernel rebuilds
        # the distance ramp in-kernel, the dense fallback materializes it.
        from ..models.transformer import alibi_slopes

        alibi = alibi_slopes(h)
    o = _cache_attend(q, attend_k, attend_v, length, flash_decode=flash_decode,
                      alibi=alibi)
    # Quantized TP decode collective (inference.tp_comm_quant): the wo
    # and dense-MLP w_out partial-sum reductions — the per-token
    # model-axis wire cost every TP decode step pays — spell as explicit
    # two-sided int8 all-reduces. 0 (default) keeps this path bit-frozen
    # on the GSPMD fp psum.
    tpq = _tp_quant_eligible(model, p, T)
    o_flat = o.reshape(B, T, h * hd)
    o = tp_quant_dot(o_flat, p["wo"], bits=tpq) if tpq else None
    if o is None:
        o = matmul_any(o_flat, p["wo"],
                       use_kernel=getattr(model, "woq_kernel", False))
    o = model._maybe_bias(o, p, "bo")
    # MoE trunks expose a single-group no-drop dispatch (_mlp_block_infer,
    # models/moe.py) for the T=1 decode step; prefill (T>1) and dense
    # trunks use the training MLP unchanged (per-row grouping keeps
    # prefill's dispatch one-hots at the training memory profile).
    moe_infer = getattr(model, "_mlp_block_infer", None) if T == 1 else None
    mlp = moe_infer or model._mlp_block
    if tpq and moe_infer is None:
        mlp = partial(_mlp_tp_quant, model, bits=tpq)
    if cfg.parallel_residual:
        y2 = y if cfg.parallel_shared_ln else _norm(
            x, p["ln2_scale"], p.get("ln2_bias"), cfg.norm, cfg.norm_eps)
        out, _aux = mlp(y2, p)
        x = x + o + out
    else:
        x = x + o
        y2 = _norm(x, p["ln2_scale"], p.get("ln2_bias"), cfg.norm,
                   cfg.norm_eps)
        out, _aux = mlp(y2, p)
        x = x + out
    if paged is not None:
        return x, cache_k, cache_v, scale_k, scale_v
    return x, cache_k, cache_v


def _embed_rows(table, ids, dtype):
    """Row gather from a dense or int8/int4-stored embedding table — a
    quantized table reads int8 bytes for exactly the batch's tokens."""
    if isinstance(table, QuantizedTensor):
        return dequant_rows(table, ids, dtype)
    return table.astype(dtype)[ids]


def _decode_head(model, params, x):
    """Final norm + unembedding for the decode path, in fp32.

    Differences from the training head that matter per token:
    - logits come out of the MXU in fp32 (``preferred_element_type``)
      and STAY fp32 into the sampler — the old path rounded the dot to
      bf16 and the sampler cast straight back, a pure bf16↔fp32
      round-trip over (B, V) every step;
    - a quantized tied table is consumed in (V, d) layout by the fused
      transposed WOQ GEMM (``woq_dot_t``) — the unembedding, the single
      largest weight read of a decode step, streams int8;
    - no (V, d) transpose is ever materialized for the dense tied case
      either (``dot_general`` contracts the table's last dim directly).
    """
    cfg = model.cfg
    x = model._pre_head(params, x)
    use_kernel = getattr(model, "woq_kernel", False)
    w = params["tok_embed"] if cfg.tie_embeddings else params["lm_head"]
    if isinstance(w, QuantizedTensor):
        dot = woq_dot_t if cfg.tie_embeddings else woq_dot
        logits = dot(x, w, use_kernel=use_kernel, out_dtype=jnp.float32)
    elif cfg.tiled_head > 1 and w.shape[0 if cfg.tie_embeddings else 1] \
            % cfg.tiled_head == 0 and x.shape[1] > 1:
        # big-vocab prefill through the public API: keep the tiled head
        # (bounds the (B, T, V) logits working set; the generation loop
        # never lands here — its prefill slices to the last position)
        from ..ops.tiled import tiled_matmul

        w2 = (w.T if cfg.tie_embeddings else w).astype(x.dtype)
        logits = tiled_matmul(x, w2, cfg.tiled_head)
    elif cfg.tie_embeddings:
        logits = lax.dot_general(
            x, w.astype(x.dtype), (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        logits = lax.dot_general(
            x, w.astype(x.dtype), (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    if cfg.lm_head_bias:
        logits = logits + params["lm_head_bias"].astype(logits.dtype)
    return constrain(logits, P(BATCH_AXES, None, "model"))


def forward_with_cache(model, params, input_ids, cache: KVCache,
                       positions=None, flash_decode: bool = False,
                       last_token_head: bool = False, last_index=None):
    """Run T tokens through all layers, appending to the cache.

    input_ids: (B, T). Works for both prefill (T = prompt length, cache
    empty) and decode (T = 1). Returns (fp32 logits (B, T, V), new cache).
    ``cache.length`` may be a scalar (every row at the same position) or a
    (B,) per-slot vector (serving: each slot appends at its own length).
    ``cache`` may also be a :class:`PagedKVCache` (decode-side T: the
    plain step's 1 or the speculative verify's max_draft + 1): appends
    scatter through the slot page tables and the attention read gathers
    each slot's pages — page-table CONTENTS are data, so traffic churn
    never changes the program.
    ``last_token_head=True`` computes the unembedding only for the final
    position (the generation loop's prefill: the other T-1 logit rows are
    discarded anyway, and at GPT-2 vocab sizes they're the biggest tensor
    of the whole prefill); ``last_index`` (traced i32 scalar) overrides
    which position that is — the serving engine's right-padded final
    prefill chunk puts the last real token at ``true_len - 1``, not T-1.
    """
    cfg = model.cfg
    B, T = input_ids.shape
    paged = isinstance(cache, PagedKVCache)
    # Paged T > 1 is the serving engine's speculative verify forward
    # (carry token + drafts in one fixed-shape call); its headroom gate
    # keeps every live slot's post-append length within max_len. Prefill
    # still runs through a contiguous per-request cache and is scattered
    # into pages at insert (serving/pages.py).
    new_len = cache.length + T
    per_slot = getattr(cache.length, "ndim", 0) == 1
    if positions is None:
        base = cache.length[:, None] if per_slot else cache.length
        positions = base + jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    x = _embed_rows(params["tok_embed"], input_ids, cfg.dtype)
    if cfg.pos_embedding == "learned":
        if per_slot:   # rows sit at different positions: per-row gather
            x = x + _embed_rows(params["pos_embed"], positions, cfg.dtype)
        else:
            x = x + _embed_rows(params["pos_embed"], positions[0],
                                cfg.dtype)[None]
    if cfg.embed_norm:
        x = _norm(x, params["embed_ln_scale"], params.get("embed_ln_bias"),
                  cfg.norm, cfg.norm_eps)

    if paged:
        def paged_scan(carry, layer_in):
            x = carry
            lp, ck, cv, ks, vs = layer_in
            x, ck, cv, ks, vs = _layer_step(
                model, x, lp, ck, cv, new_len, positions,
                flash_decode=flash_decode,
                paged=(cache.page_table, ks, vs))
            return x, (ck, cv, ks, vs)

        x, (ck, cv, ks, vs) = lax.scan(
            paged_scan, x, (params["layers"], cache.k, cache.v,
                            cache.k_scale, cache.v_scale))
        new_cache = PagedKVCache(k=ck, v=cv, k_scale=ks, v_scale=vs,
                                 page_table=cache.page_table, length=new_len)
    else:
        def scan_fn(carry, layer_in):
            x = carry
            lp, ck, cv = layer_in
            x, ck, cv = _layer_step(model, x, lp, ck, cv, new_len, positions,
                                    flash_decode=flash_decode)
            return x, (ck, cv)

        x, (ck, cv) = lax.scan(scan_fn,
                               x, (params["layers"], cache.k, cache.v))
        new_cache = KVCache(k=ck, v=cv, length=new_len)
    if last_token_head:
        x = x[:, -1:] if last_index is None else \
            lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
    logits = _decode_head(model, params, x)
    return logits, new_cache


class GenCarry(NamedTuple):
    """Generation state between the prefill and the decode scan.

    ``rng`` is one (2,) key (whole-batch sampling stream) or a (B, 2)
    per-row key stack — each row then advances its own independent chain,
    so a request folded from its own seed samples identically whether it
    runs alone, in a static batch, or through the serving scheduler."""

    tok: jnp.ndarray         # (B,) i32 — latest sampled token
    cache: KVCache
    rng: jnp.ndarray         # (2,) or (B, 2) uint32
    done: jnp.ndarray        # (B,) bool — eos reached


def prefill_tokens(model, params, input_ids, rng, *, max_new: int,
                   sampler, eos_token_id=None, cache_dtype=None,
                   flash_decode: bool = False, materialize=None,
                   cache_len=None) -> GenCarry:
    """Prompt → first sampled token + primed KV cache (the TTFT phase).

    ``cache_len`` overrides the tight ``S + max_new`` cache allocation —
    the serving layer buckets cache shapes so one compiled program serves
    many (prompt, max_new) combinations; positions past the live length
    are masked either way.

    ``materialize``: optional ``quantized params -> dense params`` fn,
    applied ONLY here (prefill is compute-bound; dense is right there).
    The decode scan consumes ``params`` as given: a quantized tree stays
    int8/int4 end-to-end — every projection dispatches through
    ``matmul_any``/``woq_dot_t`` at its point of use, so the weight bytes
    re-read from HBM each token are the quantized ones. The old
    alternative (re-materializing the whole tree in the scan body and
    hoping XLA fuses the convert) measurably did not fuse — XLA hoisted
    the loop-invariant dequant and decode re-read a bf16 copy
    (``WOQ_PROBE.json`` round 5) — which is why the consumption sites
    dispatch explicitly now.
    """
    from .sampling import split_keys

    objective = getattr(model.cfg, "objective", "clm")
    if objective != "clm":
        raise ValueError(
            f"generation needs a causal LM head; this model's objective is "
            f"{objective!r} — use forward() (MLM logits / feature hidden "
            "states) instead")
    B, S = input_ids.shape
    if cache_len is None:
        cache_len = S + max_new
    elif cache_len < S + max_new:
        raise ValueError(f"cache_len={cache_len} < prompt + max_new "
                         f"= {S + max_new}")
    if flash_decode:
        # round up to the Pallas decode kernel's 128-lane block: the spare
        # slots are masked by the live length, and every decode step stays
        # on the streaming kernel regardless of prompt/output lengths
        cache_len = -(-cache_len // 128) * 128
    cache = init_cache(model.cfg, B, cache_len, cache_dtype or model.cfg.dtype)
    mat = materialize if materialize is not None else (lambda p: p)

    with jax.named_scope("prefill"):
        logits, cache = forward_with_cache(model, mat(params), input_ids,
                                           cache, last_token_head=True)
    rng, sub = split_keys(rng)
    tok = sampler(logits[:, -1], sub)
    done = (tok == eos_token_id) if eos_token_id is not None \
        else jnp.zeros((B,), bool)
    return GenCarry(tok=tok, cache=cache, rng=rng, done=done)


def decode_step(model, params, carry: GenCarry, *, sampler,
                eos_token_id=None, flash_decode: bool = False,
                logit_guard: bool = False, poison_row=None):
    """ONE decode iteration: forward the carry token, sample the next.

    The single definition shared by :func:`decode_tokens`' scan body and
    the serving engine's slot step (``serving/slots.py``), so the eos
    forcing and rng-split order cannot drift between the static-batch and
    continuous-batching paths — that shared order is what makes serving
    outputs bit-identical to single-request ``generate()``.

    ``logit_guard=True`` (the serving step) additionally returns a (B,)
    bool of per-row logit finiteness — ``(carry, ok)`` — computed on
    device and read back fused with the step's existing tok/done sync, so
    the guard adds ZERO host syncs. Sampling is unchanged either way.

    ``poison_row`` (chaos only; a traced i32 scalar, -1 = none) overwrites
    that one row's logits with NaN before sampling — AFTER the forward, so
    the poison can never reach the KV cache or any other row. ``where``
    with a false mask returns the original logits bit-exactly, so a chaos
    program running with poison_row=-1 matches the clean program."""
    from .sampling import split_keys

    tok, cache, rng, done = carry
    with jax.named_scope("decode_step"):
        lg, cache = forward_with_cache(model, params, tok[:, None], cache,
                                       flash_decode=flash_decode)
    if poison_row is not None:
        bad = jnp.arange(lg.shape[0], dtype=jnp.int32)[:, None, None] \
            == poison_row
        lg = jnp.where(bad, jnp.float32(float("nan")), lg)
    rng, sub = split_keys(rng)
    nxt = sampler(lg[:, 0], sub)
    if eos_token_id is not None:
        nxt = jnp.where(done, eos_token_id, nxt)
        done = done | (nxt == eos_token_id)
    out = GenCarry(nxt, cache, rng, done)
    if logit_guard:
        return out, jnp.all(jnp.isfinite(lg), axis=(1, 2))
    return out


def decode_tokens(model, params, carry: GenCarry, *, steps: int, sampler,
                  eos_token_id=None, flash_decode: bool = False,
                  return_carry: bool = False):
    """Decode scan: ``steps`` more tokens after the carry's.

    Returns (B, steps + 1) — the carry token plus everything it generated
    — or ``(tokens, carry)`` with ``return_carry=True`` (the engine's
    chunked-decode path resumes the scan from the returned carry after a
    host-side ``done.all()`` check). The KV cache threads through the scan
    carry, so XLA reuses (donates) the cache buffers in place — cache
    update and attend live in the same scan body with no copy between
    steps.
    """

    def step(carry, _):
        nxt = decode_step(model, params, carry, sampler=sampler,
                          eos_token_id=eos_token_id,
                          flash_decode=flash_decode)
        return nxt, carry.tok

    out, toks = lax.scan(step, carry, None, length=steps)
    # emitted tokens 0..steps-1 plus the final carry token. Constrain both
    # concat operands to an explicit replicated layout first: under TP the
    # partitioner resolves the scan-stacked ys and the carry token to
    # DIFFERENT shardings, and (jax 0.4.x GSPMD) reconciles them with a
    # spurious cross-shard reduce — every emitted token id summed tp_size
    # times. Token ids are (steps, B) int32 — replication is free next to
    # a decode step, and the constraint is a no-op off-mesh.
    tokens = jnp.concatenate([constrain(toks, P(None, None)),
                              constrain(out.tok[None], P(None, None))],
                             axis=0).T                     # (B, steps + 1)
    return (tokens, out) if return_carry else tokens


def generate_tokens(model, params, input_ids, rng, *, max_new: int,
                    sampler, eos_token_id=None, cache_dtype=None,
                    flash_decode: bool = False, materialize=None,
                    cache_len=None):
    """Shared prefill + decode-scan generation loop, as ONE traceable fn.

    Used by both :class:`~deepspeed_tpu.inference.InferenceEngine` and the
    RLHF :class:`~deepspeed_tpu.runtime.hybrid_engine.HybridEngine` so the
    schedule/eos logic cannot drift between them. ``sampler(logits, rng)``
    -> (B,) int32.

    Composes :func:`prefill_tokens` + :func:`decode_tokens` inside one
    trace — jitted as a unit this is the zero-host-sync fast path (nothing
    leaves the device between prompt in and tokens out). The engine's
    request-tracing mode jits the two halves separately instead, buying an
    honest TTFT / per-token-latency split for exactly one extra host sync
    per request (see ``InferenceEngine.generate``).
    """
    carry = prefill_tokens(model, params, input_ids, rng, max_new=max_new,
                           sampler=sampler, eos_token_id=eos_token_id,
                           cache_dtype=cache_dtype, flash_decode=flash_decode,
                           materialize=materialize, cache_len=cache_len)
    return decode_tokens(model, params, carry, steps=max_new - 1,
                         sampler=sampler, eos_token_id=eos_token_id,
                         flash_decode=flash_decode)
