"""Inference stack: KV-cache decode engine, sampling, weight-only quant.

Reference analog: ``deepspeed/inference/engine.py:39`` (InferenceEngine),
the kernel-injection machinery (``module_inject/replace_module.py``), the
fused decode kernels (``csrc/transformer/inference/``), and weight-only
quantization (``inference/quantization``). TPU-native: the per-token decode
path is one jitted scan with a static-shape KV cache (the CUDA-graph
capture/replay of ``inference/engine.py:517`` is subsumed by XLA
compilation), TP falls out of the same param sharding specs as training,
and there is no module surgery — the model is already functional.
"""

from .config import InferenceConfig, ServingConfig
from .engine import InferenceEngine, init_inference

__all__ = ["InferenceConfig", "ServingConfig", "InferenceEngine",
           "init_inference"]
