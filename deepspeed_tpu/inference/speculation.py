"""Self-speculative (prompt-lookup) drafting: the shared n-gram helper.

Reference analog: DeepSpeed-FastGen / Medusa-class speculative decoding,
restricted to the draft-free variant — the "draft model" is an n-gram
table over the request's OWN token history (prompt + everything emitted
so far), so acceptance is pure profit on repetitive traffic and zero
extra weights are resident. The same table spelling serves three
consumers, which is the whole point of this module:

- the OFFLINE estimator (``observability/workload.py:selfspec_acceptance``)
  that prices the lever before it is switched on,
- the LIVE drafter inside ``serving/engine.py``'s decode lane, and
- the replay backtest that checks predicted-vs-achieved acceptance.

One implementation means predicted and achieved acceptance cannot drift
by construction. The serving engine verifies drafts with a single
fixed-shape length-``max_draft + 1`` forward (chunked-prefill spelling:
the number of ACCEPTED tokens is host-side data, never a compile shape),
and under greedy sampling the verified stream is bit-identical to plain
decode — see ``docs/SERVING.md``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

__all__ = ["SpeculationConfig", "NGramTable", "acceptance_stats"]


@dataclass
class SpeculationConfig:
    """The ``serving.speculation`` block.

    ``ngram`` is the context length of the lookup table (matches the
    workload estimator's ``ngram`` so the estimator prices exactly the
    drafter that runs); ``max_draft`` is the per-step draft ceiling, so
    the verify forward is a fixed ``max_draft + 1``-token program.
    Speculation requires greedy sampling (the parity guarantee is
    argmax-chaining); the serving engine enforces that at construction.
    """

    enabled: bool = True
    ngram: int = 3
    max_draft: int = 4

    def __post_init__(self):
        if self.ngram < 1:
            raise ValueError(f"speculation.ngram must be >= 1, got {self.ngram}")
        if self.max_draft < 1:
            raise ValueError(
                f"speculation.max_draft must be >= 1, got {self.max_draft}")

    @classmethod
    def from_any(cls, obj) -> "SpeculationConfig":
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            unknown = set(obj) - {f for f in cls.__dataclass_fields__}
            if unknown:
                raise ValueError(
                    f"unknown speculation config keys: {sorted(unknown)}")
            return cls(**obj)
        raise TypeError(f"cannot build SpeculationConfig from {type(obj)!r}")


class NGramTable:
    """Most-recent-occurrence n-gram lookup over one token stream.

    ``extend`` feeds tokens in order; each full ``ngram``-length context
    maps to the token that followed it, last write wins. ``predict``
    looks up the CURRENT trailing context, ``draft`` chains predictions
    (feeding each predicted token back as context) until the table has
    no entry or ``k`` tokens are drafted. The estimator's
    predict-then-extend loop reproduces the historical
    lookup-before-insert scoring exactly.
    """

    __slots__ = ("ngram", "_table", "_ctx")

    def __init__(self, ngram: int):
        if ngram < 1:
            raise ValueError(f"ngram must be >= 1, got {ngram}")
        self.ngram = int(ngram)
        self._table: dict = {}
        self._ctx: tuple = ()

    def __len__(self) -> int:
        return len(self._table)

    def extend(self, tokens: Sequence[int]) -> None:
        ctx, n = self._ctx, self.ngram
        for t in tokens:
            t = int(t)
            if len(ctx) == n:
                self._table[ctx] = t
            ctx = (ctx + (t,))[-n:]
        self._ctx = ctx

    def predict(self) -> Optional[int]:
        if len(self._ctx) != self.ngram:
            return None
        return self._table.get(self._ctx)

    def draft(self, k: int) -> list:
        """Chain up to ``k`` predictions from the trailing context.

        The chain stops at the first context with no table entry; the
        speculative continuation is only as long as the history supports.
        Chaining mutates nothing — the table and trailing context are
        restored before returning, so a draft is a pure read.
        """
        if len(self._ctx) != self.ngram or k <= 0:
            return []
        out = []
        ctx = self._ctx
        for _ in range(k):
            pred = self._table.get(ctx)
            if pred is None:
                break
            out.append(pred)
            ctx = (ctx + (pred,))[-self.ngram:]
        return out


def acceptance_stats(tokens, ngram: int) -> Optional[dict]:
    """Score a finished token stream as if the prompt-lookup drafter had
    run over it: at each position past the first ``ngram`` tokens, would
    the table (built from the stream so far) have predicted the actual
    next token?

    Returns None when the stream is too short to score, else a dict:

    - ``scored``: positions scored (``len(tokens) - ngram``),
    - ``predicted``: positions where the table HAD a prediction,
    - ``hits``: positions where that prediction matched,
    - ``rate``: ``hits / scored`` — the historical estimator semantics
      (no-prediction counts as a miss), and
    - ``hit_rate``: ``hits / predicted`` — the conditional rate, which
      is what the LIVE drafter's first-draft accept rate converges to
      (the live drafter simply doesn't propose when there's no entry).
    """
    toks = np.asarray(tokens).reshape(-1).tolist()
    n = len(toks)
    if n <= ngram:
        return None
    tab = NGramTable(ngram)
    tab.extend(toks[:ngram])
    hits = predicted = 0
    for t in toks[ngram:]:
        pred = tab.predict()
        if pred is not None:
            predicted += 1
            if pred == int(t):
                hits += 1
        tab.extend((int(t),))
    scored = n - ngram
    return {
        "scored": scored,
        "predicted": predicted,
        "hits": hits,
        "rate": hits / scored,
        "hit_rate": (hits / predicted) if predicted else None,
    }
