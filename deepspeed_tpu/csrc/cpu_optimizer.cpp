// Host-side fused optimizers for offloaded fp32 master state.
//
// TPU-native equivalent of the reference's csrc/adam/cpu_adam_impl.cpp,
// csrc/adagrad/cpu_adagrad.cpp, csrc/lion/cpu_lion*.cpp (AVX512/AVX256
// intrinsics + OpenMP, csrc/includes/simd.h). Here the SIMD comes from the
// compiler (-O3 -march=native -fopenmp, `omp simd` inner loops autovectorize
// to the same AVX fma sequences), the threading from OpenMP, and the
// "simultaneous fp16 param copy" of the reference is a simultaneous *bf16*
// copy-back (the dtype the TPU compute step consumes).
//
// Update semantics mirror deepspeed_tpu/runtime/optimizers.py exactly so the
// host path is bit-compatible (up to fp contraction) with the XLA path.

#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

inline uint16_t f32_to_bf16(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  // round-to-nearest-even on the truncated mantissa
  uint32_t rounding = 0x7FFFu + ((x >> 16) & 1u);
  return static_cast<uint16_t>((x + rounding) >> 16);
}

}  // namespace

extern "C" {

// AdamW (adamw=1, decoupled decay) / Adam (adamw=0, L2 in grad).
// If p_bf16 != nullptr, also writes the updated param as bf16 (the
// reference's simultaneous half-precision copy, cpu_adam_impl.cpp).
void ds_adam_step(float* p, float* m, float* v, const float* g, int64_t n,
                  int64_t step, float lr, float beta1, float beta2, float eps,
                  float weight_decay, int adamw, int bias_correction,
                  uint16_t* p_bf16) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
    bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  }
  const float om_b1 = 1.0f - beta1;
  const float om_b2 = 1.0f - beta2;

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i];
    if (!adamw && weight_decay != 0.0f) grad += weight_decay * p[i];
    float mi = beta1 * m[i] + om_b1 * grad;
    float vi = beta2 * v[i] + om_b2 * grad * grad;
    float upd = (mi / bc1) / (std::sqrt(vi / bc2) + eps);
    if (adamw && weight_decay != 0.0f) upd += weight_decay * p[i];
    float pi = p[i] - lr * upd;
    m[i] = mi;
    v[i] = vi;
    p[i] = pi;
    if (p_bf16) p_bf16[i] = f32_to_bf16(pi);
  }
}

// Lion (runtime/optimizers.py lion()): update = sign(b1*m + (1-b1)*g) + wd*p
void ds_lion_step(float* p, float* m, const float* g, int64_t n, float lr,
                  float beta1, float beta2, float weight_decay,
                  uint16_t* p_bf16) {
  const float om_b1 = 1.0f - beta1;
  const float om_b2 = 1.0f - beta2;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i];
    float c = beta1 * m[i] + om_b1 * grad;
    float upd = (c > 0.0f) ? 1.0f : ((c < 0.0f) ? -1.0f : 0.0f);
    upd += weight_decay * p[i];
    float pi = p[i] - lr * upd;
    m[i] = beta2 * m[i] + om_b2 * grad;
    p[i] = pi;
    if (p_bf16) p_bf16[i] = f32_to_bf16(pi);
  }
}

// Adagrad (runtime/optimizers.py adagrad())
void ds_adagrad_step(float* p, float* acc, const float* g, int64_t n,
                     float lr, float eps, float weight_decay,
                     uint16_t* p_bf16) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i];
    if (weight_decay != 0.0f) grad += weight_decay * p[i];
    float a = acc[i] + grad * grad;
    float pi = p[i] - lr * grad / (std::sqrt(a) + eps);
    acc[i] = a;
    p[i] = pi;
    if (p_bf16) p_bf16[i] = f32_to_bf16(pi);
  }
}

// bf16 <-> f32 bulk converts for the offload transfer path.
void ds_bf16_to_f32(const uint16_t* src, float* dst, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t x = static_cast<uint32_t>(src[i]) << 16;
    std::memcpy(&dst[i], &x, 4);
  }
}

void ds_f32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) dst[i] = f32_to_bf16(src[i]);
}

int ds_num_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
