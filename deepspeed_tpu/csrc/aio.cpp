// Async file I/O for the NVMe offload tier (ZeRO-Infinity analog).
//
// TPU-native equivalent of the reference's csrc/aio/ (2,942 LoC of
// libaio-based C++: worker threads in deepspeed_aio_thread.cpp, pinned
// buffer manager, queue-depth/block-size config). Design here: a fixed
// worker-thread pool draining a submission queue of pread/pwrite jobs
// against O_DIRECT file descriptors (falling back to buffered I/O where
// O_DIRECT is unsupported, e.g. tmpfs), completion signalled per-ticket.
// Threads + O_DIRECT saturate NVMe queue depth the same way io_submit
// does, without requiring libaio/liburing at build time.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <cerrno>
#include <fcntl.h>
#include <unistd.h>

namespace {

struct Job {
  int64_t ticket;
  bool write;
  int fd;
  void* buf;
  int64_t nbytes;
  int64_t offset;
};

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Job> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::atomic<bool> stop{false};
  int64_t next_ticket = 1;
  int64_t completed_through = 0;   // all tickets <= this are done
  std::vector<int64_t> done_list;  // out-of-order completions
  std::atomic<int64_t> errors{0};
  int block_size;

  explicit Handle(int n_threads, int block) : block_size(block) {
    for (int t = 0; t < n_threads; ++t)
      workers.emplace_back([this] { this->run(); });
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& w : workers) w.join();
  }

  void run() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        job = queue.front();
        queue.pop_front();
      }
      bool ok = true;
      char* p = static_cast<char*>(job.buf);
      int64_t left = job.nbytes, off = job.offset;
      while (left > 0) {
        int64_t chunk = left < block_size ? left : block_size;
        ssize_t r = job.write ? pwrite(job.fd, p, chunk, off)
                              : pread(job.fd, p, chunk, off);
        if (r < 0 && errno == EINVAL) {
          // O_DIRECT alignment violation (unaligned user buffer / offset /
          // fs without O_DIRECT support): drop the flag and retry buffered.
          int fl = fcntl(job.fd, F_GETFL);
          if (fl >= 0 && (fl & O_DIRECT)) {
            fcntl(job.fd, F_SETFL, fl & ~O_DIRECT);
            continue;
          }
        }
        if (r <= 0) { ok = false; break; }
        p += r; off += r; left -= r;
      }
      if (!ok) errors.fetch_add(1);
      {
        std::lock_guard<std::mutex> lk(mu);
        done_list.push_back(job.ticket);
        // advance the contiguous completion frontier
        bool moved = true;
        while (moved) {
          moved = false;
          for (size_t i = 0; i < done_list.size(); ++i) {
            if (done_list[i] == completed_through + 1) {
              completed_through++;
              done_list[i] = done_list.back();
              done_list.pop_back();
              moved = true;
              break;
            }
          }
        }
      }
      done_cv.notify_all();
    }
  }

  int64_t submit(bool write, int fd, void* buf, int64_t n, int64_t off) {
    int64_t t;
    {
      std::lock_guard<std::mutex> lk(mu);
      t = next_ticket++;
      queue.push_back(Job{t, write, fd, buf, n, off});
    }
    cv.notify_one();
    return t;
  }

  void wait(int64_t ticket) {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [this, ticket] { return completed_through >= ticket; });
  }
};

}  // namespace

extern "C" {

void* ds_aio_create(int n_threads, int block_size) {
  if (n_threads <= 0) n_threads = 4;
  if (block_size <= 0) block_size = 1 << 20;
  return new Handle(n_threads, block_size);
}

void ds_aio_destroy(void* h) { delete static_cast<Handle*>(h); }

// O_DIRECT if possible (real NVMe), buffered otherwise (tmpfs, overlayfs).
int ds_aio_open(const char* path, int for_write, int direct) {
  int flags = for_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
  if (direct) {
    int fd = open(path, flags | O_DIRECT, 0644);
    if (fd >= 0) return fd;
  }
  return open(path, flags, 0644);
}

void ds_aio_close(int fd) { close(fd); }

int64_t ds_aio_submit_read(void* h, int fd, void* buf, int64_t nbytes,
                           int64_t offset) {
  return static_cast<Handle*>(h)->submit(false, fd, buf, nbytes, offset);
}

int64_t ds_aio_submit_write(void* h, int fd, void* buf, int64_t nbytes,
                            int64_t offset) {
  return static_cast<Handle*>(h)->submit(true, fd, buf, nbytes, offset);
}

void ds_aio_wait(void* h, int64_t ticket) {
  static_cast<Handle*>(h)->wait(ticket);
}

int64_t ds_aio_errors(void* h) {
  return static_cast<Handle*>(h)->errors.load();
}

}  // extern "C"
