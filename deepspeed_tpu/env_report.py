"""Environment report CLI (``dstpu_report``).

Analog of the reference's ``ds_report`` (``env_report.py``): versions,
platform/device inventory, memory kinds, and the op compatibility matrix —
which ops have a native/Pallas implementation available right now and which
fall back.
"""

from __future__ import annotations

import sys


def collect_report() -> dict:
    import jax
    import jaxlib
    import numpy as np

    import deepspeed_tpu

    from .ops.builder import op_report
    from .ops.registry import available_ops
    from .platform.accelerator import get_accelerator

    acc = get_accelerator()
    versions = {
        "deepspeed_tpu": deepspeed_tpu.__version__,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "numpy": np.__version__,
        "python": sys.version.split()[0],
    }
    try:
        import orbax.checkpoint as ocp

        versions["orbax-checkpoint"] = getattr(ocp, "__version__", "?")
    except Exception:
        versions["orbax-checkpoint"] = "MISSING"
    return {
        "versions": versions,
        "platform": acc.platform,
        "devices": acc.device_count(),
        "local_devices": acc.local_device_count(),
        "processes": acc.process_count(),
        "memory_kinds": list(acc.memory_kinds()),
        "host_offload": acc.supports_host_offload(),
        "native_ops": op_report(),
        "registered_ops": available_ops(),
    }


def main() -> None:
    rep = collect_report()
    line = "-" * 60
    print(line)
    print("deepspeed_tpu environment report (ds_report analog)")
    print(line)
    for k, v in rep["versions"].items():
        print(f"{k:<20} {v}")
    print(line)
    print(f"{'platform':<20} {rep['platform']}")
    print(f"{'devices':<20} {rep['devices']} "
          f"(local {rep['local_devices']}, processes {rep['processes']})")
    print(f"{'memory kinds':<20} {', '.join(rep['memory_kinds'])}")
    print(f"{'host offload':<20} {rep['host_offload']}")
    print(line)
    print("op compatibility (native build status):")
    for name, ok in sorted(rep["native_ops"].items()):
        print(f"  {name:<26} {'OKAY' if ok else 'python-fallback'}")
    print("registered ops: " + ", ".join(rep["registered_ops"]))
    print(line)


if __name__ == "__main__":
    main()
