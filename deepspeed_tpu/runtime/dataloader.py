"""Data loading.

Analog of ``runtime/dataloader.py`` (``DeepSpeedDataLoader`` /
``RepeatingLoader``): a DP-sharded loader that hands each host its slice of
the global batch as numpy dicts; the engine assembles them into global sharded
``jax.Array``s. Works with any iterable/indexable dataset of dict samples.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional, Sequence

import jax
import numpy as np


class RepeatingLoader:
    """Wraps an iterator to restart when exhausted (reference ``:17``)."""

    def __init__(self, loader):
        self.loader = loader
        self._iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = iter(self.loader)
            return next(self._iter)


class DataLoader:
    """Per-host batches of per-host size ``local_batch_size`` =
    train_batch_size_per_step // process_count.

    ``sampler_offset`` supports curriculum/resume: deterministic shuffling is
    keyed by (seed, epoch) like a DistributedSampler.
    """

    def __init__(self, dataset: Sequence[dict] | Any, local_batch_size: int,
                 *, shuffle: bool = True, seed: int = 0, drop_last: bool = True,
                 collate_fn: Optional[Callable] = None):
        self.dataset = dataset
        self.local_batch_size = local_batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or self._default_collate
        self.epoch = 0
        self.rank = jax.process_index()
        self.world = jax.process_count()

    @staticmethod
    def _default_collate(samples: list[dict]) -> dict:
        keys = samples[0].keys()
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in keys}

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self):
        n = len(self.dataset) // self.world
        if self.drop_last:
            return n // self.local_batch_size
        return (n + self.local_batch_size - 1) // self.local_batch_size

    def __iter__(self) -> Iterator[dict]:
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        # contiguous per-host slice so global assembly is a pure concat
        per_host = n // self.world
        idx = idx[self.rank * per_host:(self.rank + 1) * per_host]
        bs = self.local_batch_size
        stop = (len(idx) // bs) * bs if self.drop_last else len(idx)
        for i in range(0, stop, bs):
            chunk = [self.dataset[int(j)] for j in idx[i:i + bs]]
            yield self.collate_fn(chunk)


def random_token_dataset(n_samples: int, seq_len: int, vocab_size: int,
                         seed: int = 0, learnable: bool = False) -> list[dict]:
    """Synthetic LM data (analog of the reference tests' ``random_dataloader``).

    ``learnable=True`` emits constant-token sequences — a trivially learnable
    bigram task so loss-decreases oracles converge in a handful of steps.
    """
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_samples):
        if learnable:
            tok = rng.integers(0, vocab_size)
            ids = np.full((seq_len,), tok, dtype=np.int32)
        else:
            ids = rng.integers(0, vocab_size, (seq_len,), dtype=np.int32)
        out.append({"input_ids": ids})
    return out
