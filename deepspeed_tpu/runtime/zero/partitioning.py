"""ZeRO stages as sharding rules.

The reference implements ZeRO as optimizer subclasses with per-param mutation,
grad hooks, and a fetch coordinator (``runtime/zero/stage_1_and_2.py:96``,
``stage3.py:72``, ``partitioned_param_coordinator.py:58``). Under XLA the same
partitioning semantics are *compiled into the step* as sharding choices over
the ``data`` mesh axis:

- **stage 0**: params, grads, optimizer state replicated; gradients
  all-reduced (XLA inserts the all-reduce because the batch is sharded).
- **stage 1**: fp32 master params + optimizer state sharded over ``data``;
  the optimizer update runs shard-wise, and the cast back to the compute
  dtype all-gathers the updated params — exactly the reference's
  "update partition, then allgather" step (``stage_1_and_2.py:1699``).
- **stage 2**: additionally, gradients are constrained to the master sharding
  *before* the update, so XLA lowers the grad reduction to reduce-scatter
  instead of all-reduce (the IPG-bucket reduce-scatter of
  ``stage_1_and_2.py:1270``), never materializing full replicated grads.
- **stage 3**: compute params are sharded over ``data`` too; the per-layer
  all-gather that ``PartitionedParameterCoordinator.fetch_sub_module`` does
  eagerly is emitted by XLA inside the (scanned) forward/backward, overlapped
  by the latency-hiding scheduler. Small params stay replicated below
  ``param_persistence_threshold`` (same knob as the reference).

TP/EP sharding composes: a param's model-defined :class:`PartitionSpec` (the
``model``/``expert`` axes) is augmented with ``data`` on a free dimension.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...config import ZeroConfig


def _spec_entries(spec: PartitionSpec | None, rank: int) -> list:
    entries = list(spec) if spec is not None else []
    entries += [None] * (rank - len(entries))
    return entries


def _axis_factor(entry, mesh: Mesh) -> int:
    if entry is None:
        return 1
    names = entry if isinstance(entry, (tuple, list)) else (entry,)
    return int(np.prod([mesh.shape[a] for a in names]))


def add_axis_to_spec(spec: Optional[PartitionSpec], shape: tuple[int, ...],
                     mesh: Mesh, axis="data",
                     skip_dims: tuple[int, ...] = ()) -> PartitionSpec:
    """Shard one more dimension of ``shape`` over ``axis`` (a mesh axis name
    or tuple of names, sharded jointly), composing with the existing ``spec``.
    Picks the largest free (unsharded, divisible) dim; falls back to stacking
    onto an already-sharded dim; returns ``spec`` unchanged (replicated
    w.r.t. ``axis``) if nothing divides.
    """
    names = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    names = tuple(a for a in names if mesh.shape[a] > 1)
    size = int(np.prod([mesh.shape[a] for a in names])) if names else 1
    if size == 1:
        return spec if spec is not None else PartitionSpec()
    entries = _spec_entries(spec, len(shape))
    if any(a in (e if isinstance(e, (tuple, list)) else (e,))
           for e in entries if e is not None for a in names):
        return PartitionSpec(*entries)

    # Prefer free dims, largest first (ties → later dims, which are usually
    # the contraction/output dims that XLA gathers most efficiently).
    candidates = sorted(
        (d for d in range(len(shape)) if d not in skip_dims),
        key=lambda d: (entries[d] is not None, -shape[d], -d),
    )
    for d in candidates:
        existing = _axis_factor(entries[d], mesh)
        if shape[d] % (existing * size) == 0:
            if entries[d] is None:
                entries[d] = names if len(names) > 1 else names[0]
            else:
                prev = entries[d] if isinstance(entries[d], (tuple, list)) else (entries[d],)
                entries[d] = tuple(prev) + names
            return PartitionSpec(*entries)
    return PartitionSpec(*entries)


def param_size(shape: tuple[int, ...]) -> int:
    return int(np.prod(shape)) if shape else 1


class ZeroPartitioner:
    """Computes compute/master sharding trees for a model's params."""

    def __init__(self, zero_config: ZeroConfig, mesh: Mesh,
                 scan_dims: int = 0):
        self.cfg = zero_config
        self.mesh = mesh
        # Leading dims that a `lax.scan` over layers iterates; sharding those
        # over `data` would turn balanced all-gathers into single-owner
        # broadcasts, so they are excluded from partitioning.
        self.scan_dims = scan_dims
        self.has_zero_axis = mesh.shape.get("zero", 1) > 1
        self.hpz = self.has_zero_axis and int(zero_config.zero_hpz_partition_size) > 1
        self.mics = self.has_zero_axis and int(zero_config.mics_shard_size or 0) > 0

    @property
    def dp_axes(self) -> tuple:
        """Axes the full DP/ZeRO partition spans. Under MiCS the partition
        group is only the ``zero`` subgroup (state replicated across groups,
        reference runtime/zero/mics.py:55)."""
        if self.mics:
            return ("zero",)
        return ("data", "zero") if self.has_zero_axis else ("data",)

    # ------------------------------------------------------------- per-param
    def compute_spec(self, model_spec: Optional[PartitionSpec],
                     shape: tuple[int, ...], *, stacked: bool = False) -> PartitionSpec:
        """Sharding of the (bf16) compute copy of a param."""
        base = model_spec if model_spec is not None else PartitionSpec()
        if self.cfg.stage < 3:
            return base
        if param_size(shape) < int(self.cfg.param_persistence_threshold):
            return base
        skip = tuple(range(1 if stacked else 0))
        # hpZ: the secondary (compute) shard spans only the fast ``zero``
        # subgroup, so per-layer forward all-gathers never leave it
        # (reference ZeRO++ hpZ, partition_parameters.py:1032).
        axes = ("zero",) if self.hpz else self.dp_axes
        return add_axis_to_spec(base, shape, self.mesh, axes, skip_dims=skip)

    def master_spec(self, model_spec: Optional[PartitionSpec],
                    shape: tuple[int, ...], *, stacked: bool = False) -> PartitionSpec:
        """Sharding of fp32 master params and optimizer moments."""
        base = model_spec if model_spec is not None else PartitionSpec()
        if self.cfg.stage < 1:
            return base
        skip = tuple(range(1 if stacked else 0))
        return add_axis_to_spec(base, shape, self.mesh, self.dp_axes,
                                skip_dims=skip)

    # ----------------------------------------------------------------- trees
    def _tree_map_specs(self, fn, model_specs, shapes, stacked_fn):
        return jax.tree.map(
            lambda spec, shp: fn(spec, tuple(shp), stacked=stacked_fn(shp)),
            model_specs, shapes,
            is_leaf=lambda x: x is None or isinstance(x, PartitionSpec),
        )

    def compute_specs(self, model_specs, shapes, stacked_fn=lambda s: False):
        return self._tree_map_specs(self.compute_spec, model_specs, shapes, stacked_fn)

    def master_specs(self, model_specs, shapes, stacked_fn=lambda s: False):
        return self._tree_map_specs(self.master_spec, model_specs, shapes, stacked_fn)

    # ------------------------------------------------------------------ grads
    def grad_spec_tree(self, master_specs):
        """Stage >= 2: constrain grads to the master sharding so the reduction
        lowers to reduce-scatter. Stage < 2: leave to XLA (all-reduce)."""
        if self.cfg.stage >= 2:
            return master_specs
        return None


def shardings_from_specs(mesh: Mesh, specs) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s if s is not None else PartitionSpec()),
        specs, is_leaf=lambda x: x is None or isinstance(x, PartitionSpec))
