"""LR schedules.

Capability analog of the reference ``runtime/lr_schedules.py`` (763 LoC):
LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR, WarmupCosineLR — implemented
as jittable ``step -> lr`` functions so the schedule value is computed inside
the compiled train step (no host round-trip per step).
"""

from __future__ import annotations

import math
from typing import Callable

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def constant(lr: float) -> Schedule:
    return lambda step: jnp.float32(lr)


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000, warmup_type: str = "log") -> Schedule:
    """Reference ``WarmupLR``: warm up then hold at max."""
    wmin, wmax, wsteps = float(warmup_min_lr), float(warmup_max_lr), max(1, warmup_num_steps)

    def sched(step):
        s = jnp.minimum(step.astype(jnp.float32), wsteps)
        if warmup_type == "log":
            frac = jnp.log1p(s) / math.log1p(wsteps)
        else:
            frac = s / wsteps
        return wmin + (wmax - wmin) * frac

    return sched


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3, warmup_num_steps: int = 1000,
                    warmup_type: str = "log") -> Schedule:
    """Reference ``WarmupDecayLR``: warmup then linear decay to 0."""
    warm = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    total = max(1, total_num_steps)
    wsteps = max(1, warmup_num_steps)

    def sched(step):
        s = step.astype(jnp.float32)
        decay = jnp.clip((total - s) / max(1, total - wsteps), 0.0, 1.0)
        return jnp.where(s < wsteps, warm(step), warmup_max_lr * decay)

    return sched


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 1e-4,
                     warmup_max_lr: float = 1e-3) -> Schedule:
    total = max(1, total_num_steps)
    wsteps = max(1, warmup_num_steps)

    def sched(step):
        s = step.astype(jnp.float32)
        warm_frac = warmup_min_ratio + (1 - warmup_min_ratio) * jnp.minimum(s / wsteps, 1.0)
        prog = jnp.clip((s - wsteps) / max(1, total - wsteps), 0.0, 1.0)
        cos = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + jnp.cos(jnp.pi * prog))
        return warmup_max_lr * jnp.where(s < wsteps, warm_frac, cos)

    return sched


def one_cycle(cycle_min_lr: float, cycle_max_lr: float, cycle_first_step_size: int = 2000,
              cycle_second_step_size: int | None = None, decay_step_size: int = 0,
              decay_lr_rate: float = 0.0) -> Schedule:
    """Reference ``OneCycle`` (triangular up/down then optional decay)."""
    up = max(1, cycle_first_step_size)
    down = max(1, cycle_second_step_size or cycle_first_step_size)

    def sched(step):
        s = step.astype(jnp.float32)
        in_up = s < up
        in_down = (s >= up) & (s < up + down)
        frac_up = jnp.clip(s / up, 0.0, 1.0)
        frac_down = jnp.clip((s - up) / down, 0.0, 1.0)
        lr_up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * frac_up
        lr_down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * frac_down
        post = s - (up + down)
        if decay_step_size > 0:
            decay = jnp.maximum(0.0, 1.0 - decay_lr_rate * (post / decay_step_size))
        else:
            decay = 1.0
        lr_post = cycle_min_lr * decay
        return jnp.where(in_up, lr_up, jnp.where(in_down, lr_down, lr_post))

    return sched


def lr_range_test(lr_range_test_min_lr: float = 1e-3, lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Schedule:
    """Reference ``LRRangeTest``: linearly/staircase increasing probe."""
    base, size, rate = lr_range_test_min_lr, max(1, lr_range_test_step_size), lr_range_test_step_rate

    def sched(step):
        s = step.astype(jnp.float32)
        interval = jnp.floor(s / size) if lr_range_test_staircase else s / size
        return base * (1.0 + interval * rate)

    return sched


SCHEDULES = {
    "warmuplr": warmup_lr,
    "warmupdecaylr": warmup_decay_lr,
    "warmupcosinelr": warmup_cosine_lr,
    "onecycle": one_cycle,
    "lrrangetest": lr_range_test,
    "constant": lambda lr=1e-3, **_: constant(lr),
}


def build_schedule(sched_type: str | None, params: dict, fallback_lr: float) -> Schedule:
    """ds_config ``scheduler`` → schedule; no scheduler → constant optimizer lr."""
    if sched_type is None:
        return constant(fallback_lr)
    key = sched_type.lower().replace("_", "")
    if key not in SCHEDULES:
        raise ValueError(f"unknown scheduler '{sched_type}' (have {sorted(SCHEDULES)})")
    p = dict(params)
    if key in ("warmuplr", "warmupdecaylr", "warmupcosinelr") and "warmup_max_lr" not in p:
        p["warmup_max_lr"] = fallback_lr
    return SCHEDULES[key](**p)
