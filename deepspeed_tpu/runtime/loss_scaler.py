"""Dynamic loss scaling for fp16.

Analog of the reference ``runtime/fp16/loss_scaler.py:42`` (DynamicLossScaler)
and the global overflow check (``stage3.py:1998-2054``): scale the loss,
detect non-finite grads with one global reduction, skip the step and back off
the scale on overflow, grow it after a stable window. Fully jittable —
the skip/backoff is `jnp.where` data-flow, not Python control flow.

bf16 (the TPU-native path) does not need this and runs with scale==1.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ..config import FP16Config


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 current loss scale
    good_steps: jnp.ndarray     # i32 consecutive non-overflow steps
    hysteresis: jnp.ndarray     # i32 remaining hysteresis budget


def init_loss_scale(cfg: FP16Config) -> LossScaleState:
    if not cfg.enabled:
        return LossScaleState(scale=jnp.float32(1.0), good_steps=jnp.int32(0),
                              hysteresis=jnp.int32(cfg.hysteresis))
    init = cfg.loss_scale if cfg.loss_scale > 0 else float(2 ** cfg.initial_scale_power)
    return LossScaleState(scale=jnp.float32(init), good_steps=jnp.int32(0),
                          hysteresis=jnp.int32(cfg.hysteresis))


def grads_finite(grads: Any) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(grads)
    finites = [jnp.all(jnp.isfinite(l)) for l in leaves]
    return jnp.all(jnp.stack(finites)) if finites else jnp.bool_(True)


def update_loss_scale(state: LossScaleState, finite: jnp.ndarray,
                      cfg: FP16Config) -> LossScaleState:
    if not cfg.enabled or cfg.loss_scale > 0:  # static scale: never move
        return state
    window = cfg.loss_scale_window
    # overflow: consume hysteresis; halve only when exhausted (reference
    # ``loss_scaler.py`` hysteresis semantics)
    hys_left = jnp.maximum(state.hysteresis - 1, 0)
    backoff_scale = jnp.maximum(state.scale * 0.5, cfg.min_loss_scale)
    overflow_scale = jnp.where(state.hysteresis <= 1, backoff_scale, state.scale)
    # stable window: double
    grown = state.good_steps + 1
    grow = grown >= window
    good_scale = jnp.where(grow, state.scale * 2.0, state.scale)
    return LossScaleState(
        scale=jnp.where(finite, good_scale, overflow_scale),
        good_steps=jnp.where(finite, jnp.where(grow, 0, grown), 0).astype(jnp.int32),
        hysteresis=jnp.where(finite, jnp.int32(cfg.hysteresis),
                             hys_left.astype(jnp.int32)),
    )
