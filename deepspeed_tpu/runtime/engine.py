"""Training engine.

TPU-native analog of ``DeepSpeedEngine`` (``runtime/engine.py:175``, 3.5 kLoC).
The reference wraps the model and orchestrates forward/backward/step
imperatively with hooks, streams, and bucketed collectives; here the entire
micro-step pipeline — gradient accumulation (``lax.scan`` over micro-batches,
replacing the ``is_gradient_accumulation_boundary`` bookkeeping), mixed
precision casts, loss scaling, ZeRO-sharded gradient reduction, clipping, and
the optimizer update — is one jitted, donated function. XLA's latency-hiding
scheduler provides the comm/compute overlap that the reference hand-codes
with side streams (``overlap_comm``).

API shape follows the reference: ``initialize(config, model, ...)`` returns an
engine with ``train_batch`` / ``eval_batch`` / ``save_checkpoint`` /
``load_checkpoint`` / ``client_lr_scheduler``-style accessors.
"""

from __future__ import annotations

import dataclasses
import math
import os
from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..observability.spans import TRAIN_PHASE, TRAIN_STEP
from ..platform.accelerator import get_accelerator
from ..platform.mesh import (BATCH_AXES, MeshSpec, build_mesh, dp_world_size)
from ..utils.logging import log_dist, logger
from ..utils.timer import ThroughputTimer, WallClockTimers, peak_flops_for
from .loss_scaler import (LossScaleState, grads_finite, init_loss_scale,
                          update_loss_scale)
from .lr_schedules import build_schedule
from .onebit import in_warmup
from .optimizers import OptState, Optimizer, build_optimizer
from .sparse_grads import SparseGradRows
from .zero.partitioning import ZeroPartitioner, shardings_from_specs


class TrainState(NamedTuple):
    step: jnp.ndarray              # i32 global step
    master_params: Any             # fp32, ZeRO-sharded per stage
    opt_state: OptState            # same sharding as master
    loss_scale: LossScaleState
    skipped_steps: jnp.ndarray     # i32 (fp16 overflow skips)
    # 1-bit compression error-feedback residuals (worker/server), per data
    # rank (reference runtime/fp16/onebit/adam.py worker_error/server_error);
    # () when compression is off.
    comm_err: Any = ()


# Activation names the trunk tags with jax.ad_checkpoint.checkpoint_name
# (models/transformer.py _layer, models/t5.py): the residual stream entering
# each layer and the projected attention output. The offload policy below
# moves exactly these to pinned host memory during the forward — the TPU
# shape of the reference's cpu_checkpointing + contiguous_checkpointing
# (activation_checkpointing/checkpointing.py:1036): HBM holds ~one layer's
# activations while host RAM holds the rest, and XLA's latency-hiding
# scheduler overlaps the D2H/H2D streams with layer compute.
OFFLOAD_ACTIVATION_NAMES = ("layer_in", "attn_out")


def _remat_policy(cfg: Config):
    if not cfg.remat.enabled:
        return None
    name = cfg.remat.policy
    cp = jax.checkpoint_policies
    table = {
        "none": None,
        "full": cp.nothing_saveable,
        "save_nothing": cp.nothing_saveable,
        "dots_saveable": cp.dots_saveable,
        # Save ONLY the tagged layer-boundary activations (the residual
        # stream entering each layer + the projected attention output) and
        # recompute everything else in the backward. Under flash attention
        # this is ~4x less saved HBM per layer than dots_saveable (which
        # keeps every projection/MLP dot output) — the policy that lets a
        # 1B-param decoder train on one 16 GiB chip without host offload.
        "save_names": cp.save_only_these_names(*OFFLOAD_ACTIVATION_NAMES),
        # save_names + the pre-activation MLP intermediate (~3x the saved
        # bytes of save_names, still ~40% of dots_saveable): trades ~1 GiB
        # of HBM at 1B/mbs4 for skipping the w_in matmul recompute in the
        # backward — the largest single dot in the layer.
        "save_names_mlp": cp.save_only_these_names(
            *OFFLOAD_ACTIVATION_NAMES, "mlp_h"),
    }
    if name == "offload_dots":
        return cp.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=list(OFFLOAD_ACTIVATION_NAMES),
            offload_src="device", offload_dst="pinned_host")
    return table.get(name, cp.dots_saveable)


class Engine:
    """Owns mesh, sharded state, and the compiled train/eval steps."""

    def __init__(self, config: Config | dict | str | None, model,
                 mesh: Optional[Mesh] = None, seed: Optional[int] = None,
                 params=None, abstract_state: bool = False):
        self.config = Config.from_any(config)
        self.model = model
        # AOT-probe mode (params-per-chip ceiling search): state is a tree
        # of sharding-annotated ShapeDtypeStructs — NOTHING is materialized
        # in device or host memory, so configs far past the OOM line can
        # still be compile-probed via compile_train_step. Only
        # compile_train_step is usable on such an engine.
        self._abstract = bool(abstract_state)
        # pretrained initial weights (HF import, numpy/jax trees): become
        # the fp32 master instead of model.init(rng) — the zero.Init-style
        # born-sharded construction still applies (passed as a jit argument,
        # resharded by out_shardings, never baked in as constants)
        self._initial_params = params
        de = self.config.data_efficiency
        self.curriculum = None
        if de.curriculum_learning.enabled:
            from ..data_pipeline.curriculum import CurriculumScheduler

            self.curriculum = CurriculumScheduler.from_config(
                de.curriculum_learning)
        self._ltd = de.random_ltd if de.random_ltd.enabled else None
        self._ltd_tokens = -1
        self._warned_device_batch = False
        self._flops_nominal_checked = False
        self._comp = self.config.compression.enabled_techniques()
        self._moq = None
        if self._comp:
            from ..compression import convert_to_compressed

            self.model = model = convert_to_compressed(
                model, self.config.compression)
            wq = self.config.compression.weight_quantization
            if wq.enabled and wq.start_bits and wq.start_bits > wq.bits:
                from ..compression.moq import MoQScheduler

                self._moq = MoQScheduler(wq)
                self._moq_probe_batch = None
        if self.config.lora.enabled:
            from .lora import convert_to_lora

            self.model = model = convert_to_lora(
                model, rank=self.config.lora.rank,
                alpha=self.config.lora.alpha)
        self._pld = self.config.progressive_layer_drop.enabled
        if self._pld:
            from .progressive_layer_drop import convert_to_progressive_layer_drop

            pld = self.config.progressive_layer_drop
            self.model = model = convert_to_progressive_layer_drop(
                model, theta=pld.theta, gamma=pld.gamma)
        # Frozen-param mask (LoRA base weights): a static bool pytree; the
        # update step restores frozen leaves AFTER the optimizer math, so
        # neither gradients nor weight decay can drift them.
        self._frozen_mask = (model.frozen_param_mask()
                             if hasattr(model, "frozen_param_mask") else None)
        if self.config.checkpoint.use_node_local_storage:
            raise ValueError(
                "checkpoint.use_node_local_storage is not supported: the "
                "orbax store is one logical checkpoint written collectively "
                "(per-host shard files are an artifact of the reference's "
                "torch.save layout); point save_dir at local storage instead")
        if self.config.prescale_gradients:
            raise ValueError(
                "prescale_gradients has no effect under XLA: the gradient "
                "reduction order is compiler-managed (no pre-allreduce "
                "division point exists), and fp16 overflow is handled by "
                "dynamic loss scaling — remove the flag")
        mcfg = self.config.moe
        if mcfg.enabled:
            # ds_config moe section overrides the model's MoE knobs
            # (reference wires these through the engine into MOELayer)
            if getattr(model.cfg, "num_experts", 1) != mcfg.num_experts:
                raise ValueError(
                    f"config.moe.num_experts={mcfg.num_experts} but the model "
                    f"was built with {getattr(model.cfg, 'num_experts', 1)}")
            model.cfg = dataclasses.replace(
                model.cfg, moe_top_k=mcfg.top_k,
                moe_capacity_factor=mcfg.capacity_factor,
                moe_eval_capacity_factor=mcfg.eval_capacity_factor,
                moe_min_capacity=mcfg.min_capacity,
                moe_drop_tokens=mcfg.drop_tokens,
                moe_aux_loss_weight=mcfg.aux_loss_weight)
        if self.config.comms_logger.enabled:
            from ..comm.comm import comms_logger as _cl

            _cl.enabled = True
            _cl.verbose = self.config.comms_logger.verbose
        # the one-shot HLO collective census runs for the comms logger's
        # summary AND for the commscope observatory's static-bytes side
        # of the achieved-bandwidth ledger (observability/commscope.py)
        _cs_cfg = self.config.observability.commscope
        self._comms_logged = not (self.config.comms_logger.enabled
                                  or bool(_cs_cfg
                                          and _cs_cfg.get("enabled")))
        if self._ltd is not None:
            from ..data_pipeline.random_ltd import convert_to_random_ltd

            self.model = model = convert_to_random_ltd(model,
                                                       seed=self._ltd.seed)
        self.acc = get_accelerator()
        m = self.config.mesh
        self.mesh = mesh or build_mesh(self._mesh_spec(m))
        if self._ltd is not None and int(self.mesh.shape.get("pipe", 1)) > 1:
            raise ValueError(
                "random_ltd is not supported with pipeline parallelism: the "
                "pipe shard_map scans stage-local layer slices, so the "
                "first/last-layer-full rule would apply per stage, not "
                "globally; disable one of the two")
        # PLD composes with pipeline parallelism: PLDMixin._scan_layers
        # recovers the global layer index from lax.axis_index("pipe") so the
        # depth-scaled keep probability follows the paper's global-depth
        # rule even on stage-local slices (see progressive_layer_drop.py).
        self.dp_world = dp_world_size(self.mesh)
        el = self.config.elasticity
        if el.enabled:
            from ..elasticity import ElasticityError, elastic_batch_for

            explicit = [f for f in ("train_batch_size",
                                    "train_micro_batch_size_per_gpu",
                                    "gradient_accumulation_steps")
                        if isinstance(getattr(self.config, f), int)]
            if explicit and not el.ignore_non_elastic_batch_info:
                raise ElasticityError(
                    f"elasticity.enabled with explicit {explicit}: the "
                    "elastic schema owns the batch arithmetic (set "
                    "ignore_non_elastic_batch_info to drop the explicit "
                    "values, reference behavior)")
            batch, micro, gas = elastic_batch_for(el, self.dp_world)
            self.config = self.config.model_copy(update={
                "train_batch_size": batch,
                "train_micro_batch_size_per_gpu": micro,
                "gradient_accumulation_steps": gas,
            })
            log_dist(f"elasticity: world={self.dp_world} → global={batch} "
                     f"micro={micro} gas={gas}", ranks=[0])
        self.config = self.config.resolve_batch_sizes(self.dp_world)
        self.seed = self.config.seed if seed is None else seed

        zcfg = self.config.zero_optimization
        self.offload = False
        self.partitioner = ZeroPartitioner(zcfg, self.mesh)
        gc = self.config.gradient_compression
        self.grad_comp: Optional[str] = (
            gc.type if gc.enabled
            else ("int8" if zcfg.zero_quantized_gradients else None))
        # bucketed backward-overlap dispatch (comm/compressed.py): bucket
        # size in fp32 elements, defaulting to the reference's
        # reduce_bucket_size knob. 0 buckets = the fused flat spelling.
        self.grad_overlap: bool = bool(self.grad_comp and gc.overlap)
        self._grad_bucket_elems: int = (
            (int(gc.bucket_elems) or int(zcfg.reduce_bucket_size))
            if self.grad_overlap else 0)
        if self._grad_bucket_elems and self.grad_comp != "fp":
            # every QUANTIZED bucket pads to whole per-rank scale blocks,
            # so a bucket smaller than world * BLOCK moves MORE bytes
            # than it carries — clamp to the padding quantum (the wire
            # summary still reports the padding that remains). fp buckets
            # reduce with a plain unpadded pmean: no padding to clamp for.
            from ..comm.compressed import BLOCK

            floor = int(self.mesh.shape["data"]) * BLOCK
            if self._grad_bucket_elems < floor:
                log_dist(
                    f"gradient_compression: bucket_elems="
                    f"{self._grad_bucket_elems} is below the padding "
                    f"quantum data_world*{BLOCK}={floor} (each bucket "
                    "pads to whole per-rank scale blocks) — clamped to "
                    f"{floor}", ranks=[0])
                self._grad_bucket_elems = floor
        if self.grad_comp and zcfg.stage >= 3 \
                and not (self.partitioner.hpz or self.partitioner.mics):
            raise ValueError(
                "gradient compression (qgZ / 1-bit) under ZeRO-3 requires "
                "zero_hpz_partition_size > 1 or mics_shard_size > 0: compute "
                "params must not be sharded over the compressed 'data' axis")
        if self.grad_comp and jax.__version__.startswith("0.4"):
            fast = [a for a in ("model", "seq", "expert", "zero", "pipe")
                    if int(self.mesh.shape.get(a, 1)) > 1]
            if fast:
                # Not a policy choice — 0.4's SPMD partitioner hard-ABORTS
                # the process (Check failed: sharding.IsManualSubgroup())
                # when the manual-'data' grad shard_map carries operands
                # sharded over a GSPMD-managed sub-axis. An init-time
                # typed error beats an uncatchable abort; jax >= 0.9
                # handles manual subgroups and lifts the restriction.
                raise ValueError(
                    f"gradient_compression on jax {jax.__version__} "
                    f"requires a pure-data mesh: the manual-'data' "
                    f"shard_map with GSPMD-managed {fast} axes crashes "
                    "the 0.4 SPMD partitioner (IsManualSubgroup check "
                    "abort) — drop the axes or run the jax>=0.9 image")
        from .onebit import ONEBIT_TYPES, OnebitConfig

        opt_type = self.config.optimizer.type.lower().replace("-", "_")
        opt_type = {"onebitadam": "onebit_adam", "onebitlamb": "onebit_lamb",
                    "zerooneadam": "zero_one_adam"}.get(opt_type, opt_type)
        self.onebit: Optional[OnebitConfig] = None
        if opt_type in ONEBIT_TYPES:
            self.onebit = OnebitConfig.from_params(opt_type,
                                                   self.config.optimizer.params)
            if zcfg.stage != 0:
                raise ValueError(
                    f"{opt_type} requires ZeRO stage 0 (replicated masters): "
                    "the compressed momentum collective assumes every rank "
                    "holds the full momentum (reference 1-bit optimizers "
                    "have the same restriction)")
            if self.grad_comp:
                raise ValueError(
                    f"{opt_type} already compresses its own communication; "
                    "disable gradient_compression")
            if self.config.fp16.enabled:
                raise ValueError(
                    f"{opt_type} does not support fp16 dynamic loss scaling "
                    "(no overflow-skip on the compressed-momentum path; one "
                    "bad step would poison the error-feedback residuals) — "
                    "use bf16, the TPU default")
            if self.config.gradient_clipping:
                raise ValueError(
                    f"{opt_type} does not support gradient_clipping: in the "
                    "compressed phase the global gradient is never "
                    "materialized, so a global-norm clip cannot be computed "
                    "(same restriction as the reference 1-bit optimizers)")
            # moments init/shape come from the plain Adam state tree
            base = {k: v for k, v in self.config.optimizer.params.items()
                    if k in ("lr", "betas", "eps", "weight_decay")}
            self.optimizer = build_optimizer("adamw", base)
        else:
            self.optimizer = build_optimizer(opt_type,
                                             self.config.optimizer.params)
        base_lr = float(self.config.optimizer.params.get("lr", 1e-3))
        sched_cfg = self.config.scheduler
        self.lr_schedule = build_schedule(sched_cfg.type if sched_cfg else None,
                                          sched_cfg.params if sched_cfg else {}, base_lr)
        self.remat_policy = _remat_policy(self.config)
        self.compute_dtype = self.config.compute_dtype

        # ---------------- sharding trees
        rng = jax.random.PRNGKey(self.seed)
        abstract = jax.eval_shape(self.model.init, rng)
        shapes = jax.tree.map(lambda a: a.shape, abstract)
        self._shapes = shapes
        model_specs = self.model.param_specs()
        stacked = self.model.stacked_fn() if hasattr(self.model, "stacked_fn") else (lambda s: False)
        self.compute_specs = self.partitioner.compute_specs(model_specs, shapes, stacked)
        self.master_specs = self.partitioner.master_specs(model_specs, shapes, stacked)
        self.compute_shardings = shardings_from_specs(self.mesh, self.compute_specs)
        self.master_shardings = shardings_from_specs(self.mesh, self.master_specs)
        # static layer-aligned bucket plan for the compressed/overlapped
        # grad reduction (one bucket when overlap is off — the fused flat
        # spelling, numerically unchanged)
        self._stacked_fn = stacked
        self._grad_plan = None
        if self.grad_comp:
            from ..comm.compressed import plan_buckets

            leaf_shapes = [tuple(s) for s in jax.tree.leaves(
                shapes, is_leaf=lambda x: isinstance(x, tuple))]
            self._grad_plan = plan_buckets(
                leaf_shapes, [stacked(s) for s in leaf_shapes],
                self._grad_bucket_elems)
            if self.grad_overlap and len(self._grad_plan.buckets) == 1:
                log_dist(
                    "gradient_compression.overlap: the whole grad tree "
                    f"fits one bucket ({self._grad_plan.total_elems} <= "
                    f"bucket_elems={self._grad_bucket_elems}) — the "
                    "reduction compiles to the fused flat spelling with "
                    "nothing to overlap; lower gradient_compression."
                    "bucket_elems (or zero_optimization.reduce_bucket_"
                    "size) below the param count to get bucketed "
                    "dispatch", ranks=[0])

        self.param_count = sum(int(np.prod(a.shape))
                               for a in jax.tree.leaves(abstract))
        log_dist(f"engine: {self.param_count / 1e6:.1f}M params | zero stage "
                 f"{zcfg.stage} | mesh {dict(self.mesh.shape)} | "
                 f"micro={self.config.train_micro_batch_size_per_gpu} "
                 f"gas={self.config.gradient_accumulation_steps} "
                 f"global={self.config.train_batch_size}", ranks=[0])

        # ---------------- ZeRO-Offload / Infinity: host-resident optimizer
        zoff = zcfg.offload_optimizer
        self.offload = zoff.device in ("cpu", "nvme")
        if self.offload and self._frozen_mask is not None:
            raise ValueError(
                "lora + offload_optimizer: the host optimizer has no "
                "frozen-leaf masking yet — train adapters with the device "
                "optimizer (LoRA state is small; offload buys nothing)")
        self.param_offload = False
        if zcfg.offload_param.enabled and not self.offload:
            raise ValueError(
                "offload_param requires offload_optimizer device cpu/nvme: "
                "ZeRO-Infinity param streaming operates against the "
                "host-resident optimizer (set zero_optimization."
                "offload_optimizer.device)")
        if self.offload and self._ltd is not None:
            raise ValueError(
                "random_ltd is not supported with offload_optimizer (the "
                "host-optimizer grad step is not rebuilt on schedule "
                "changes); disable one of the two")
        if self.offload and self._pld:
            raise ValueError(
                "progressive_layer_drop is not supported with "
                "offload_optimizer (the host-optimizer grad step never sets "
                "the schedule step); disable one of the two")
        if self.offload and self._comp:
            raise ValueError(
                "compression is not supported with offload_optimizer (the "
                "host-optimizer grad step does not carry the static "
                "active-technique argument); disable one of the two")
        if self.grad_comp and self.offload:
            raise ValueError(
                "gradient_compression / zero_quantized_gradients is not "
                "supported with offload_optimizer (the host-optimizer path "
                "syncs gradients outside the compressed collective); disable "
                "one of the two")
        if self.offload and self.onebit is not None:
            raise ValueError("1-bit optimizers are device-side algorithms; "
                             "offload_optimizer is not supported with them")
        if self.offload:
            self._init_offload(rng, zoff)
            self._post_init()
            return

        # ---------------- init state (sharded at construction: the zero.Init
        # analog — params are born partitioned, never materialized replicated)
        self._comm_err_shapes = {}
        if self.onebit is not None:
            from .onebit import comm_err_shapes

            self._comm_err_shapes = comm_err_shapes(
                self.param_count, int(self.mesh.shape["data"]))
        elif self.grad_comp in ("onebit", "int8"):
            # error-feedback residuals for BOTH compressed grad modes
            # (int8 historically dropped its quantization error every
            # step — the residual pair makes it unbiased like 1-bit),
            # sized from the bucket plan so each bucket's padded window
            # is a static slice of one flat vector per role
            from ..comm.compressed import plan_comm_err_shapes

            self._comm_err_shapes = plan_comm_err_shapes(
                self._grad_plan, int(self.mesh.shape["data"]))
        comm_err_shardings = {k: NamedSharding(self.mesh, P("data"))
                              for k in self._comm_err_shapes}
        # Moment shardings follow the master EXCEPT for moments the
        # optimizer doesn't keep (Lion's nu, momentum-SGD's...), which are
        # (0,)-shaped placeholders: a rank-2 ZeRO spec on those fails the
        # init jit's out_shardings before the old post-init fixup could
        # ever run (found by the 1B Lion bench candidate).
        abstract_opt = jax.eval_shape(self.optimizer.init,
                                      jax.tree.map(
                                          lambda shp: jax.ShapeDtypeStruct(
                                              shp, jnp.float32),
                                          self._shapes,
                                          is_leaf=lambda x: isinstance(x, tuple)))

        def _moment_shardings(mtree):
            return jax.tree.map(
                lambda s, x: (NamedSharding(self.mesh, P())
                              if x.shape == (0,) else s),
                self.master_shardings, mtree)

        self.state_shardings = TrainState(
            step=NamedSharding(self.mesh, P()),
            master_params=self.master_shardings,
            opt_state=OptState(mu=_moment_shardings(abstract_opt.mu),
                               nu=_moment_shardings(abstract_opt.nu),
                               count=NamedSharding(self.mesh, P())),
            loss_scale=LossScaleState(*(NamedSharding(self.mesh, P()),) * 3),
            skipped_steps=NamedSharding(self.mesh, P()),
            comm_err=comm_err_shardings,
        )
        with self.mesh:
            if self._abstract:
                shape_state = jax.eval_shape(self._init_state, rng)
                self.state = jax.tree.map(
                    lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                      sharding=s),
                    shape_state, self.state_shardings)
            elif self._initial_params is not None:
                init_fn = jax.jit(self._init_state_from,
                                  out_shardings=self.state_shardings)
                self.state: TrainState = init_fn(self._initial_params)
                self._initial_params = None   # free the host copy
            else:
                init_fn = jax.jit(self._init_state,
                                  out_shardings=self.state_shardings)
                self.state = init_fn(rng)

        self._build_train_step()
        self._eval_step = jax.jit(self._eval_step_impl,
                                  in_shardings=(self.state_shardings.master_params,
                                                self._batch_sharding(gas_dim=False)))
        self._post_init()

    def _build_train_step(self) -> None:
        """Create the jitted train step. The random-LTD kept-token count is a
        STATIC argument — the jit cache keys on (shapes, ltd_tokens), so each
        schedule quantum is one retrace and previously compiled (seqlen, r)
        variants stay cached (curriculum + LTD compose).

        With the offload_dots remat policy, the state shardings move from
        ``out_shardings`` to a constraint on the returned state: explicit
        out_shardings make jax annotate every output's buffer placement,
        and XLA's SPMD partitioner RET_CHECKs on those side-effect
        annotations when host-offloaded rematerialization is also present
        (spmd_partitioner.cc:5743, reproduced on jax 0.9.0). The constraint
        pins the same placement without the output annotations."""
        offload_remat = (self.config.remat.enabled
                         and self.config.remat.policy == "offload_dots")
        if offload_remat:
            def step_constrained(state, batch, ltd, comp, warm):
                new_state, metrics = self._train_step_impl(
                    state, batch, ltd, comp, warm)
                new_state = jax.tree.map(
                    jax.lax.with_sharding_constraint, new_state,
                    self.state_shardings)
                return new_state, metrics

            self._train_step = jax.jit(
                step_constrained,
                donate_argnums=(0,),
                static_argnums=(2, 3, 4),
                in_shardings=(self.state_shardings, self._batch_sharding()),
            )
            return
        self._train_step = jax.jit(
            self._train_step_impl,
            donate_argnums=(0,),
            static_argnums=(2, 3, 4),
            in_shardings=(self.state_shardings, self._batch_sharding()),
            out_shardings=(self.state_shardings, None),
        )

    def _mesh_spec(self, m) -> MeshSpec:
        """Resolve the ``zero`` sub-axis (ZeRO++ hpZ / MiCS subgroup) from the
        zero config. An explicit ``mesh.data`` is the TOTAL data-parallel
        degree; the subgroup is carved out of it (data = total / zero)."""
        zc = self.config.zero_optimization
        hpz = int(zc.zero_hpz_partition_size)
        mics = int(zc.mics_shard_size or 0)
        if hpz > 1 and mics > 0 and hpz != mics:
            raise ValueError(
                f"zero_hpz_partition_size ({hpz}) and mics_shard_size ({mics}) "
                "both set but disagree; they share the mesh 'zero' sub-axis")
        mzero = int(getattr(m, "zero", 1) or 1)
        if mzero < 1:
            raise ValueError(
                "mesh.zero cannot be auto (-1): the hpZ/MiCS subgroup size "
                "must be explicit (zero_hpz_partition_size / mics_shard_size)")
        want = hpz if hpz > 1 else (mics if mics > 0 else 1)
        if mzero > 1 and want > 1 and mzero != want:
            raise ValueError(
                f"mesh.zero ({mzero}) conflicts with the configured "
                f"hpZ/MiCS subgroup size ({want})")
        zsize = max(mzero, want)
        if zc.zero_quantized_weights and hpz <= 1:
            raise ValueError(
                "zero_quantized_weights needs a cross-subgroup weight gather "
                "to quantize: set zero_optimization.zero_hpz_partition_size "
                "> 1 (under MiCS or a bare mesh.zero the master shard never "
                "spans 'data', so there is no gather to compress)")
        data = m.data
        if data != -1 and zsize > 1:
            if data % zsize != 0:
                raise ValueError(
                    f"data-parallel degree {data} not divisible by "
                    f"hpZ/MiCS subgroup size {zsize}")
            data //= zsize
        return MeshSpec(data=data, model=m.model, pipe=m.pipe, seq=m.seq,
                        expert=m.expert, zero=zsize)

    def _post_init(self):
        from ..observability.metrics import MetricsRegistry

        self.timers = WallClockTimers()
        # One registry per engine: Train/* from the step loop, Memory/*
        # from the HBM watermark, Comm/* from the collective census —
        # the training half of the unified metric namespace
        # (docs/OBSERVABILITY.md). Recording is host-side floats only.
        self.metrics = MetricsRegistry()
        obs = self.config.observability
        self._trace_window = None
        if obs.trace_steps:
            from ..observability.xla import TraceWindow

            self._trace_window = TraceWindow(
                obs.trace_steps, obs.trace_dir,
                sync_fn=lambda: jax.block_until_ready(
                    self.compute_params if self.offload else self.state))
        # span ring + flight recorder + step-time anomaly detector (the
        # training half of the serving engine's observability trio); all
        # default-off, each None costing one `is not None` on the hot path
        self.spans = None
        if obs.spans:
            from ..observability.spans import SpanRecorder

            self.spans = SpanRecorder(obs.spans_ring)
        self.flight = None
        if obs.flight_dir:
            from ..observability.flight import FlightRecorder

            self.flight = FlightRecorder(
                obs.flight_dir, spans=self.spans,
                snapshots={"train": self.metrics_snapshot},
                max_dumps=obs.flight_max_dumps, job_name="train",
                registry=self.metrics)
        self._step_anomaly = None
        if obs.slo:
            from ..observability.slo import MedianMADDetector, SLOConfig

            slo = SLOConfig.from_any(obs.slo)
            if slo.step_time_mad_k:
                self._step_anomaly = MedianMADDetector(
                    slo.step_time_mad_k, slo.step_time_window,
                    slo.step_time_min_samples)
            # an enabled knob the training engine has no machinery for
            # must not be silently ignored (same stance as
            # MonitorConfig.any_enabled): ttft/tpot/error-rate and the
            # compile-storm detector are serving-side — the operator who
            # set them believes detection is on
            unwired = [k for k in ("ttft_p99_s", "tpot_p99_s",
                                   "error_rate",
                                   "compile_storm_threshold")
                       if getattr(slo, k)]
            if unwired:
                log_dist(
                    f"observability.slo: {unwired} are serving-side "
                    "knobs — the training engine only wires "
                    "step_time_mad_k; set them under the serving "
                    "config's `slo` block instead", level="WARNING")
        # communication observatory (observability/commscope.py):
        # per-step exposed-collective anatomy + achieved-bandwidth
        # ledger over the TraceWindow capture, plus straggler detection
        # on per-step stamps. None (default) = one `is not None` per
        # step, zero new programs/syncs.
        self.commscope = None
        self._hlo_by_kind = None
        if obs.commscope and obs.commscope.get("enabled"):
            from ..observability.commscope import (CommScope,
                                                   CommScopeConfig)

            self.commscope = CommScope(
                CommScopeConfig.from_any(obs.commscope),
                registry=self.metrics, spans=self.spans,
                flight=self.flight, n_devices=len(jax.devices()))
            if self.flight is not None:
                self.flight.add_snapshot_provider(
                    "commscope", self.commscope.snapshot)
        # goodput/badput wall-time ledger (observability/goodput.py):
        # Train/goodput_* decomposition of step dispatch vs compile /
        # inter-step idle / checkpoint / preemption. None (default) =
        # zero clock reads added to train_batch.
        self.goodput = None
        self._gp_stepped = False
        if obs.goodput:
            from ..observability.goodput import GoodputLedger

            self.goodput = GoodputLedger(registry=self.metrics,
                                         prefix="Train")
        # live telemetry server (observability/server.py): /metrics,
        # /healthz, /goodput, /flight + POST /flight/dump for the
        # training process. Off (default) = zero threads. Started at the
        # END of _post_init — a probe racing construction must find
        # global_steps / the resilience fields already in place.
        self.telemetry = None
        mb, gas = self.config.train_micro_batch_size_per_gpu, self.config.gradient_accumulation_steps
        try:
            peak = peak_flops_for(self.acc.current_device()) * len(jax.devices())
        except ValueError as e:
            # Unknown hardware must not abort training — only the MFU stat
            # (bench.py, where MFU *is* the artifact, keeps the hard raise).
            log_dist(f"MFU reporting disabled: {e}", level="WARNING")
            peak = 0.0
        self.throughput = ThroughputTimer(
            batch_size=int(self.config.train_batch_size),
            steps_per_output=self.config.steps_per_print,
            flops_per_sample=self._flops_per_sample(),
            peak_flops=peak,
        )
        self.global_steps = 0
        self.monitor = None
        if self.config.monitor.any_enabled():
            from ..monitor.monitor import MonitorMaster

            self.monitor = MonitorMaster(self.config.monitor)
        self.flops_profiler = None
        if self.config.flops_profiler.enabled:
            from ..profiling import FlopsProfiler

            self.flops_profiler = FlopsProfiler(self.config.flops_profiler, self)
        # ---- resilience (docs/RESILIENCE.md) ----
        res = self.config.resilience
        # non-finite/skip sentinel state (counted exactly per step on the
        # offload path, per report window on the in-device path)
        self._max_bad_steps = int(res.max_consecutive_bad_steps or 0)
        self._bad_step_streak = 0
        self._skipped_total_prev = 0.0
        # chaos: simulated SIGTERM preemption at a fixed step (env-gated;
        # None in production — the per-step cost is one `is not None`)
        from ..resilience import chaos as _chaos

        self._chaos_preempt = _chaos.preempt_step()
        # elastic-restart visibility: the agent exports the incarnation
        # index and the previous incarnation's exit code; recording them
        # here puts Train/restarts in every sink (incl. the Prometheus
        # textfile) from the first report boundary of the new incarnation
        try:
            restarts = int(os.environ.get("DSTPU_ELASTIC_RESTART", "0") or 0)
        except ValueError:
            restarts = 0
        if restarts > 0:
            self.metrics.counter("Train/restarts").inc(restarts)
            try:
                last_rc = os.environ.get("DSTPU_ELASTIC_LAST_RC")
                if last_rc is not None:
                    self.metrics.gauge("Train/last_exit_code").set(
                        float(int(last_rc)))
            except ValueError:
                pass
        # auto-resume LAST: the engine is fully built, so this is exactly
        # a user-issued load_checkpoint (verified-tag fallback included)
        if res.resume == "auto" and not self._abstract:
            from .checkpoint.engine import auto_resume

            auto_resume(self, res.resume_dir)
        # config-gated telemetry server, after every field a probe can
        # read exists (global_steps, the sentinel state, the registry)
        tele = obs.telemetry
        if tele and tele.get("enabled"):
            from ..observability.server import TelemetryConfig

            tc = TelemetryConfig.from_any(tele)
            self.serve_telemetry(port=tc.port, host=tc.host,
                                 token=tc.token)

    def _pinned_host_outputs_work(self) -> bool:
        """Compile AND run a trivial pinned_host-output jit: advertised
        memory kinds are not trustworthy (the axon tunnel backend lists
        pinned_host but the compiled step dies at run — round-2 finding)."""
        force = os.environ.get("DSTPU_HOST_GRAD_OUTS")
        if force is not None:
            return force != "0"
        if self.acc.current_device().platform != "tpu" \
                or not self.acc.supports_host_offload():
            return False
        try:
            sh = NamedSharding(self.mesh, P(), memory_kind="pinned_host")
            with self.mesh:
                out = jax.jit(lambda x: x + 1, out_shardings=sh)(
                    jnp.zeros((8,), jnp.float32))
            np.asarray(out)
            return True
        except Exception as e:
            log_dist(f"pinned_host outputs unavailable ({type(e).__name__}); "
                     "grads stay in HBM, host step fetches them", ranks=[0])
            return False

    def _init_offload(self, rng, zoff):
        """ZeRO-Offload/Infinity mode: fp32 master + moments in host DRAM
        (NVMe tier for moments), C++ host optimizer, device holds only the
        compute copy. Reference: stage_1_and_2.py:1096 + swap_tensor/."""
        from .offload import HostOffloadOptimizer

        # fp16 under offload (reference CPU Adam runs under fp16 with
        # dynamic loss scaling, stage_1_and_2.py:1096): the scale state
        # lives host-side — the grad step returns a grads_finite flag, an
        # overflow skips the host optimizer step, and the scale backs
        # off/grows with the shared update_loss_scale rules.
        self._offload_ls = init_loss_scale(self.config.fp16)

        # ZeRO-Infinity param offload: the bf16 compute copy lives in pinned
        # host memory; the model streams each layer's slice into HBM inside
        # the scan (reference partitioned_param_swapper.py:36 +
        # parameter_offload.py:342). HBM never holds the full model.
        zoff_param = self.config.zero_optimization.offload_param
        self.param_offload = zoff_param.enabled
        if self.param_offload:
            # Gate on the backend actually exposing pinned_host, not the
            # platform name: remote-tunnel TPUs may lack it and the compiled
            # step would die with an opaque backend error (round-2 finding).
            # On CPU the streaming path stays live-but-inert (CI coverage).
            tpu_plat = self.acc.current_device().platform == "tpu"
            has_pinned = self.acc.supports_host_offload()
            on_tpu = tpu_plat and has_pinned
            self.model.params_on_host = (not tpu_plat) or has_pinned
            if on_tpu:
                stacked = (self.model.stacked_fn()
                           if hasattr(self.model, "stacked_fn")
                           else (lambda s: False))
                thresh = int(self.config.zero_optimization
                             .param_persistence_threshold or 0)
                self.compute_shardings = jax.tree.map(
                    lambda sh, shp: (NamedSharding(
                        self.mesh, sh.spec, memory_kind="pinned_host")
                        if stacked(shp) and int(np.prod(shp)) >= thresh
                        else sh),
                    self.compute_shardings, self._shapes)
            elif tpu_plat:
                log_dist("offload_param: this TPU backend exposes no "
                         "pinned_host memory kind — param streaming is "
                         "inert (params stay in HBM)", ranks=[0])
            else:
                log_dist("offload_param: non-TPU platform — params stay in "
                         "(host-backed) device memory; streaming is inert",
                         ranks=[0])

        fp32_names = tuple(getattr(self.model, "fp32_param_names", lambda: ())())
        if self._abstract:
            # AOT-probe mode: no host master, no device compute copy — just
            # the sharded shape/dtype skeleton compile_train_step needs
            def _sds(path, shp, sh):
                name = (path[-1].key if hasattr(path[-1], "key")
                        else str(path[-1]))
                dt = jnp.float32 if name in fp32_names else self.compute_dtype
                return jax.ShapeDtypeStruct(shp, dt, sharding=sh)

            self.compute_params = jax.tree_util.tree_map_with_path(
                _sds, self._shapes, self.compute_shardings,
                is_leaf=lambda x: isinstance(x, tuple))
            self.host_opt = None
        else:
            if self._initial_params is not None:
                host_master = jax.tree.map(
                    lambda a: np.asarray(a, np.float32), self._initial_params)
                self._initial_params = None
            else:
                with self.mesh:
                    init_params = jax.jit(self._init_master)(rng)
                host_master = jax.tree.map(np.asarray, init_params)
                del init_params
            self.host_opt = HostOffloadOptimizer(
                host_master, self.optimizer, zoff,
                compute_dtype=self.compute_dtype, fp32_names=fp32_names,
                compute_shardings=self.compute_shardings)
            with self.mesh:
                self.compute_params = self.host_opt.device_compute_params()
        # Grad outputs land directly in pinned host memory (when the backend
        # really supports it): XLA's latency-hiding scheduler overlaps the
        # per-layer D2H with the remaining backward compute — the reference's
        # overlap-CPU-Adam-with-backward streams (stage_1_and_2.py:1096)
        # compiled into the step. Grads KEEP their compute sharding (no
        # replication, no gather inserted); only the memory space changes.
        # Gating is an executed probe, not memory_kinds() advertisement —
        # remote-tunnel backends advertise pinned_host yet fail at run
        # (round-2 finding). DSTPU_HOST_GRAD_OUTS=0/1 force-overrides.
        # sparse_gradients: plan which embedding leaves ship row-sparse
        # over the D2H (reference sparse embedding allreduce,
        # engine.py:2427). Static top-k bound = one touched row per batch
        # token; only worth it when that bound is under half the vocab.
        self._sparse_plan = {}
        if self.config.sparse_gradients:
            names = tuple(getattr(self.model, "sparse_grad_names",
                                  lambda: ())())
            tokens = self.train_batch_size * int(
                getattr(getattr(self.model, "cfg", None), "max_seq", 0) or 0)
            for path, shape in jax.tree_util.tree_flatten_with_path(
                    self._shapes,
                    is_leaf=lambda x: isinstance(x, tuple))[0]:
                name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
                if name in names and len(shape) == 2 and tokens \
                        and tokens < shape[0] // 2:
                    self._sparse_plan[name] = min(int(tokens), int(shape[0]))
            if self._sparse_plan:
                log_dist(f"sparse_gradients: row-sparse D2H for "
                         f"{sorted(self._sparse_plan)} (k={self._sparse_plan})",
                         ranks=[0])
        grad_outs = None
        if self._pinned_host_outputs_work():
            pin = lambda s: NamedSharding(self.mesh, s.spec,
                                          memory_kind="pinned_host")

            def _out_sharding(path, s):
                name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
                if name in self._sparse_plan:
                    rep = NamedSharding(self.mesh, P(),
                                        memory_kind="pinned_host")
                    return SparseGradRows(indices=rep, values=rep)
                return pin(s)

            grad_outs = jax.tree_util.tree_map_with_path(
                _out_sharding, self.compute_shardings)
        self._grad_step = jax.jit(
            self._grad_step_impl,
            in_shardings=(self.compute_shardings, self._batch_sharding(),
                          NamedSharding(self.mesh, P())),
            **({"out_shardings": (grad_outs, None)} if grad_outs else {}))
        self._eval_offload = jax.jit(
            lambda cp, b: self.model.loss(cp, b),
            in_shardings=(self.compute_shardings,
                          self._batch_sharding(gas_dim=False)))
        log_dist(f"offload: optimizer states on "
                 f"{'NVMe' if zoff.device == 'nvme' else 'host DRAM'} "
                 f"({self.param_count / 1e6:.1f}M params)", ranks=[0])

    def _init_master(self, rng):
        return jax.tree.map(lambda a: a.astype(jnp.float32),
                            self.model.init(rng))

    def fp32_params(self):
        """Full (host) fp32 master tree — the zero_to_fp32 /
        consolidated-state-dict analog, e.g. for export_hf_checkpoint."""
        if self.offload:
            return self.host_opt.master_tree()
        return jax.tree.map(lambda a: np.asarray(a, np.float32),
                            self.state.master_params)

    def _grad_step_impl(self, compute_params, batch, scale):
        """Forward+backward only — the update happens on the host. Gradient
        clipping runs on-device (one fused epilogue) so the host never
        reallocates clipped copies; grads leave the step already final
        (unscaled — fp16's loss scale is divided back out before clipping,
        with a grads_finite flag so the caller can skip the host step)."""
        grads, loss = self._gas_scan(compute_params, batch, scale)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        finite = (grads_finite(grads) if self.config.fp16.enabled
                  else jnp.bool_(True))
        grads = jax.tree.map(lambda g: g / scale, grads)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                             for g in jax.tree.leaves(grads)))
        clip = self.config.gradient_clipping
        if clip and clip > 0:
            coef = jnp.minimum(jnp.float32(1.0), clip / (gnorm + 1e-6))
            grads = jax.tree.map(lambda g: g * coef, grads)
        grads = self._sparsify_grads(grads)
        return grads, {"loss": loss, "grad_norm": gnorm,
                       "grads_finite": finite}

    def _sparsify_grads(self, grads):
        """Replace planned embedding-grad leaves with (indices, values)
        pairs selected ON DEVICE (top-k by row max-abs; the static bound
        guarantees every touched row is included), so the offload D2H
        moves k·(d+1) floats instead of V·d."""
        if not self._sparse_plan:
            return grads

        def fn(path, g):
            name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
            k = self._sparse_plan.get(name)
            if k is None or g.ndim != 2:
                return g
            score = jnp.max(jnp.abs(g), axis=1)
            _, idx = jax.lax.top_k(score, k)
            idx = idx.astype(jnp.int32)
            return SparseGradRows(indices=idx,
                                  values=jnp.take(g, idx, axis=0))

        return jax.tree_util.tree_map_with_path(fn, grads)

    def _train_batch_offload(self, batch: dict) -> dict:
        import time as _time

        self.throughput.start()
        if self.curriculum is not None:
            batch = self._apply_data_efficiency(batch)
        if not isinstance(next(iter(batch.values())), jax.Array):
            batch = self._make_global(batch)
        t0 = _time.perf_counter()
        scale = self._offload_ls.scale
        with self.mesh:
            grads, metrics = self._grad_step(self.compute_params, batch, scale)
        # host readback is the reliable barrier (block_until_ready returns
        # early over the axon tunnel); with pinned-host grad outputs the
        # device->host DMAs already ran inside the step, overlapped with
        # the tail of backward by XLA's latency-hiding scheduler.
        gnorm = float(metrics["grad_norm"])
        finite = bool(metrics["grads_finite"])
        t_bwd = _time.perf_counter() - t0
        lr = float(self.lr_schedule(jnp.int32(self.global_steps)))
        t1 = _time.perf_counter()
        if finite:
            with self.mesh:
                self.compute_params = self.host_opt.step(grads, lr)
        else:
            log_dist(f"offload fp16: non-finite grads, skipping host step "
                     f"(loss scale {float(scale):.0f})", ranks=[0])
        self._offload_ls = update_loss_scale(
            self._offload_ls, metrics["grads_finite"], self.config.fp16)
        t_host = _time.perf_counter() - t1
        self.global_steps += 1
        if self.spans is not None:
            t2 = t1 + t_host
            self.spans.emit(TRAIN_STEP, t0, t2, step=self.global_steps)
            self.spans.emit(TRAIN_PHASE, t0, t0 + t_bwd,
                            step=self.global_steps, phase="bwd")
            self.spans.emit(TRAIN_PHASE, t1, t2, step=self.global_steps,
                            phase="host_step")
        if self.commscope is not None:
            t2 = t1 + t_host
            self.commscope.on_step(
                self.global_steps, t0, t2,
                traced=(self._trace_window is not None
                        and self._trace_window.active))
            self.commscope.observe_stamps(self.global_steps,
                                          {jax.process_index(): t2})
        out = {"loss": float(metrics["loss"]), "grad_norm": gnorm, "lr": lr,
               "loss_scale": float(scale), "skipped": 0 if finite else 1,
               "bwd_s": t_bwd, "host_step_s": t_host}
        # offload reads the finite flag back every step anyway — the
        # sentinel counts exactly, window 1
        self._note_bad_steps((not finite) or not math.isfinite(out["loss"]),
                             1, out["loss"])
        if self.global_steps % self.config.steps_per_print == 0:
            stats = self.throughput.stop(report=True)
            log_dist(f"step={self.global_steps} loss={out['loss']:.4f} "
                     f"lr={lr:.3e} gnorm={gnorm:.3f}", ranks=[0])
            # same registry namespace as the in-device path, plus the
            # offload-specific phase split (backward vs host optimizer)
            self._record_step_metrics(out, stats, extra_gauges={
                "Train/bwd_s": t_bwd, "Train/host_step_s": t_host})
            self._emit_monitor_events()
        else:
            self.throughput.stop(report=False)
        if self.flops_profiler and self.flops_profiler.should_fire():
            self.flops_profiler.profile(batch)
        return out

    # ------------------------------------------------------------------ util
    def _flops_per_sample(self) -> float:
        cfg = getattr(self.model, "cfg", None)
        if cfg is not None and hasattr(cfg, "flops_per_token"):
            # flops_per_token() is ALREADY fwd+bwd (6N + attention term);
            # multiplying by 3 here triple-counted and inflated reported
            # TFLOPS/MFU 3x (round-3 audit)
            return cfg.flops_per_token() * getattr(cfg, "max_seq", 1)
        return 0.0

    def _batch_sharding(self, gas_dim: bool = True):
        # batches are dicts of arrays shaped (gas, global_micro, ...) for train
        # and (global_batch, ...) for eval
        if gas_dim:
            return NamedSharding(self.mesh, P(None, BATCH_AXES))
        return NamedSharding(self.mesh, P(BATCH_AXES))

    def _init_state(self, rng) -> TrainState:
        master = jax.tree.map(lambda a: a.astype(jnp.float32), self.model.init(rng))
        return self._state_around(master)

    def _init_state_from(self, params) -> TrainState:
        master = jax.tree.map(lambda a: jnp.asarray(a, jnp.float32), params)
        return self._state_around(master)

    def _state_around(self, master) -> TrainState:
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            master_params=master,
            opt_state=self.optimizer.init(master),
            loss_scale=init_loss_scale(self.config.fp16),
            skipped_steps=jnp.zeros((), jnp.int32),
            comm_err={k: jnp.zeros(s, jnp.float32)
                      for k, s in self._comm_err_shapes.items()},
        )

    # ------------------------------------------------------------- train step
    @staticmethod
    def _spec_has(spec, axis: str) -> bool:
        if not isinstance(spec, P):
            return False
        for e in spec:
            names = e if isinstance(e, (tuple, list)) else (e,)
            if axis in names:
                return True
        return False

    def _cast_compute(self, master):
        """bf16/fp16 compute cast; leaves named in the model's
        ``fp32_param_names()`` (e.g. MoE routers) stay fp32.

        With ZeRO++ qwZ (``zero_quantized_weights`` + hpZ), leaves whose
        secondary (compute) shard drops the ``data`` axis are gathered as
        int8 + per-row scales instead of bf16 — the cross-subgroup weight
        all-gather moves 2x fewer bytes (4x vs fp32), the TPU shape of the
        reference's quantized weight gather
        (``runtime/zero/partition_parameters.py:1032``)."""
        keep = set(getattr(self.model, "fp32_param_names", lambda: ())())
        qwz = (self.config.zero_optimization.zero_quantized_weights
               and self.partitioner.hpz)

        def cast(path, p, mspec, cspec):
            name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
            if name in keep:
                return p
            if qwz and self._spec_has(mspec, "data") \
                    and not self._spec_has(cspec, "data"):
                from ..ops.quant import rowwise_dequant, rowwise_quant_int8

                q, s = rowwise_quant_int8(p)
                # Pin the int8 payload (and scales) to the secondary-shard
                # sharding: GSPMD emits the 'data'-axis all-gather on int8.
                q = jax.lax.with_sharding_constraint(q, cspec)
                s = jax.lax.with_sharding_constraint(
                    s, P(*(tuple(cspec)[:p.ndim - 1] if len(tuple(cspec))
                           else ()), None))
                return rowwise_dequant(q, s, self.compute_dtype)
            return p.astype(self.compute_dtype)

        cp = jax.tree_util.tree_map_with_path(cast, master, self.master_specs,
                                              self.compute_specs)
        return jax.lax.with_sharding_constraint(cp, self.compute_specs)

    def _gas_scan(self, compute_params, batch, scale):
        """Gradient-accumulation scan: (params, (gas, B, ...) batch) →
        (summed grads, mean loss). Runs either directly under jit (GSPMD
        inserts the cross-data grad reduction) or inside the manual-data
        shard_map of the compressed path (no data reduction inserted; the
        carry is seeded from the device-varying batch, so it needs no
        explicit pcast-to-varying)."""
        cfg = self.config
        gas = int(cfg.gradient_accumulation_steps)

        def loss_fn(cp, mb):
            loss = self.model.loss(cp, mb, remat_policy=self.remat_policy)
            return loss * scale / gas

        grad_fn = jax.value_and_grad(loss_fn, argnums=0)
        acc_name = cfg.data_types.grad_accum_dtype or "float32"
        acc_dtype = jnp.dtype({"fp32": "float32", "bf16": "bfloat16",
                               "fp16": "float16"}.get(acc_name, acc_name))

        def gas_body(carry, mb):
            g_acc, loss_acc = carry
            scaled_loss, g = grad_fn(compute_params, mb)
            g_acc = jax.tree.map(lambda a, gg: a + gg.astype(acc_dtype), g_acc, g)
            return (g_acc, loss_acc + scaled_loss / scale), None

        # Seed the accumulator from the FIRST micro-batch instead of zeros:
        # XLA materializes a zeros-initialized carry as a live grad-sized
        # buffer alongside each micro's grads (round-5 OOM dump: 1.17 GiB
        # of broadcast(0) for the two MLP grad leaves alone at 1B params),
        # while seeding aliases the first grads straight into the carry.
        # gas == 1 skips the scan machinery entirely.
        first = jax.tree.map(lambda t: t[0], batch)
        scaled_loss0, g0 = grad_fn(compute_params, first)
        grads0 = jax.tree.map(lambda g: g.astype(acc_dtype), g0)
        carry = (grads0, scaled_loss0 / scale)
        if gas == 1:
            return carry
        rest = jax.tree.map(lambda t: t[1:], batch)
        (grads, loss), _ = lax.scan(gas_body, carry, rest)
        return grads, loss

    def _compressed_grads(self, compute_params, batch, scale, comm_err):
        """Per-rank local grads under a manual-``data`` shard_map + explicit
        bucketed reduction (qgZ int8 / 1-bit error feedback / fp). The fast
        sub-axes (zero/expert/seq/model) stay GSPMD-managed inside — only the
        slow data hop moves compressed bytes.

        With ``gradient_compression.overlap`` the reduction runs per
        layer-aligned bucket (``comm/compressed.py plan_buckets``): each
        bucket's collective depends only on its own layers' grads, so
        XLA's latency-hiding scheduler dispatches bucket i's quantized
        wire time against the remaining backward / the neighbouring
        buckets' quantize compute instead of serializing ONE flat
        collective after the whole backward. Both compressed modes carry
        error-feedback residuals in the ``comm_err`` state (unscaled —
        true gradient units, loss-scale-change safe); fp mode is bitwise
        identical to the fused flat spelling by construction."""
        from ..comm.compressed import bucketed_grad_reduce

        D = int(self.mesh.shape["data"])
        mode = self.grad_comp
        plan = self._grad_plan
        stacked_fn = self._stacked_fn

        def body(cp, b, ce):
            grads, loss = self._gas_scan(cp, b, scale)
            # scale is divided out per bucket BEFORE compressing so the
            # error-feedback residuals are stored in true gradient units —
            # otherwise a dynamic loss-scale change would leave stale
            # residuals off by the scale ratio.
            red, nw, ns = bucketed_grad_reduce(
                grads, plan, mode=mode, axis="data",
                stacked_fn=stacked_fn, scale=scale,
                worker_err=ce["worker"][0] if "worker" in ce else None,
                server_err=ce["server"][0] if "server" in ce else None)
            if nw is not None:
                ce = {"worker": nw[None], "server": ns[None]}
            loss = lax.pmean(loss, "data")
            return red, loss, ce

        # check_vma=False: grads/loss really are replicated over 'data' (they
        # come out of an all-gather of identical chunks + a pmean), but the
        # vma inference can't prove it and would reject the P() out_specs.
        fn = jax.shard_map(
            body, mesh=self.mesh, axis_names=frozenset({"data"}),
            in_specs=(P(), P(None, "data"), P("data")),
            out_specs=(P(), P(), P("data")), check_vma=False)
        return fn(compute_params, batch, comm_err)

    def _train_step_impl(self, state: TrainState, batch: dict,
                         ltd_tokens: int = 0, comp_active: tuple = (),
                         onebit_warmup: bool = False):
        cfg = self.config
        if self._ltd is not None:
            # static per-trace constant; set before the loss is traced
            self.model.set_ltd_tokens(ltd_tokens)
        if self._comp:
            self.model.set_compression_active(comp_active)
        if self._pld:
            # traced scalar: the keep-prob schedule is continuous, no retrace
            self.model.set_pld_step(state.step)
        if self.onebit is not None:
            from .onebit import onebit_train_step

            new_master, new_opt, new_ce, loss, gnorm, lr = onebit_train_step(
                self, state, batch, jnp.float32(1.0), onebit_warmup)
            if self._pld:
                self.model.set_pld_step(None)   # don't leak the tracer
            new_state = TrainState(
                step=state.step + 1, master_params=new_master,
                opt_state=new_opt, loss_scale=state.loss_scale,
                skipped_steps=state.skipped_steps, comm_err=new_ce)
            return new_state, {"loss": loss, "grad_norm": gnorm, "lr": lr,
                               "loss_scale": jnp.float32(1.0),
                               "skipped": jnp.int32(0)}
        scale = state.loss_scale.scale

        compute_params = self._cast_compute(state.master_params)

        new_comm = state.comm_err
        if self.grad_comp:
            grads, loss, new_comm = self._compressed_grads(
                compute_params, batch, scale, state.comm_err)
        else:
            grads, loss = self._gas_scan(compute_params, batch, scale)

        # ZeRO >= 2: constrain grads to the master (partitioned) sharding so the
        # cross-data reduction lowers to reduce-scatter, not all-reduce (in the
        # compressed path the reduction already happened; this slices locally).
        grad_specs = self.partitioner.grad_spec_tree(self.master_specs)
        if grad_specs is not None:
            grads = jax.lax.with_sharding_constraint(grads, grad_specs)

        if self.grad_comp:  # compressed path already unscaled inside
            grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        else:
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) / scale, grads)
        finite = grads_finite(grads) if cfg.fp16.enabled else jnp.bool_(True)
        # Never let an overflow step poison the error-feedback residuals.
        if self.grad_comp and self._comm_err_shapes:
            new_comm = jax.tree.map(lambda n, o: jnp.where(finite, n, o),
                                    new_comm, state.comm_err)

        # gradient clipping (reference engine gradient_clipping / global norm)
        if cfg.gradient_clipping and cfg.gradient_clipping > 0:
            gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                 for g in jax.tree.leaves(grads)))
            clip = jnp.minimum(1.0, cfg.gradient_clipping / (gnorm + 1e-6))
            grads = jax.tree.map(lambda g: g * clip, grads)
        else:
            gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                 for g in jax.tree.leaves(grads)))

        lr = self.lr_schedule(state.step)

        def do_update(_):
            new_master, new_opt = self.optimizer.update(
                state.master_params, state.opt_state, grads, lr)
            if self._frozen_mask is not None:
                # static selection: XLA dead-code-eliminates the frozen
                # leaves' optimizer math entirely
                new_master = jax.tree.map(
                    lambda frozen, new, old: old if frozen else new,
                    self._frozen_mask, new_master, state.master_params)
            return new_master, new_opt, jnp.int32(0)

        def skip_update(_):
            return state.master_params, state.opt_state, jnp.int32(1)

        new_master, new_opt, skipped = lax.cond(finite, do_update, skip_update, None)
        new_ls = update_loss_scale(state.loss_scale, finite, cfg.fp16)

        if self._pld:
            self.model.set_pld_step(None)   # the traced step must not leak
        new_state = TrainState(
            step=state.step + 1,
            master_params=new_master,
            opt_state=new_opt,
            loss_scale=new_ls,
            skipped_steps=state.skipped_steps + skipped,
            comm_err=new_comm,
        )
        metrics = {"loss": loss, "grad_norm": gnorm, "lr": lr,
                   "loss_scale": scale, "skipped": skipped}
        return new_state, metrics

    def _check_flops_nominal(self, batch: dict) -> None:
        """One-time honesty check on MFU accounting: flops_per_sample is
        computed from the model config's *nominal* lengths (max_seq, or
        max_src/max_tgt for encoder-decoder), so if the actual batches
        carry a different token count the reported TFLOPS/MFU scale with
        the mismatch. Warn loudly rather than silently report wrong MFU
        (the headline number must not depend on a config default)."""
        if self._flops_nominal_checked:
            return
        self._flops_nominal_checked = True
        cfg = getattr(self.model, "cfg", None)
        nominal = getattr(cfg, "max_seq", None) if cfg is not None else None
        ids = batch.get("input_ids") if isinstance(batch, dict) else None
        if not nominal or ids is None or getattr(ids, "ndim", 0) < 2:
            return
        actual = ids.shape[-1]
        labels = batch.get("labels")
        if hasattr(cfg, "max_src") and getattr(labels, "ndim", 0) >= 2:
            actual += labels.shape[-1]   # encoder-decoder: separate targets
        if actual != nominal:
            log_dist(
                f"WARNING: MFU/TFLOPS accounting assumes {nominal} "
                f"tokens/sample (model config nominal lengths) but batches "
                f"carry {actual}; reported MFU is off by ~{nominal/actual:.2f}x "
                "— set max_seq (or max_src/max_tgt) to the real lengths",
                ranks=[0])

    def _eval_step_impl(self, master_params, batch: dict):
        cp = self._cast_compute(master_params)
        if self._ltd is not None:
            # eval ALWAYS runs the full sequence — token dropping is a
            # training-cost technique, not an eval semantic
            self.model.set_ltd_tokens(0)
        if self._comp:
            # eval sees the fully-compressed network (what would be exported)
            self.model.set_compression_active(
                tuple(sorted(n for n, _ in self._comp)))
        if self._pld:
            self.model.set_pld_step(None)   # eval runs every layer
        if getattr(self.model.cfg, "num_experts", 1) > 1:
            # trace-time flag: eval capacity factor (reference
            # eval_capacity_factor) applies in this trace only — finally
            # guarantees a failed trace can't leak it into a later train trace
            self.model.moe_eval_mode = True
            try:
                return self.model.loss(cp, batch)
            finally:
                self.model.moe_eval_mode = False
        return self.model.loss(cp, batch)

    # ------------------------------------------------------------ public API
    def _make_global(self, batch: dict, gas_dim: bool = True) -> dict:
        """Per-host numpy batch → global sharded jax.Arrays.

        Train batches: (gas * micro * local_dp, ...) per host, reshaped to
        (gas, local_batch, ...) then assembled along the batch dim.
        """
        cfg = self.config
        gas = int(cfg.gradient_accumulation_steps)
        sharding = self._batch_sharding(gas_dim)

        def to_global(x):
            x = np.asarray(x)
            if gas_dim:
                local = x.shape[0] // gas
                x = x.reshape((gas, local) + x.shape[1:])
            return jax.make_array_from_process_local_data(sharding, x)

        return {k: to_global(v) for k, v in batch.items()}

    # ------------------------------------------------- data efficiency hooks
    def _ltd_schedule_tokens(self, step: int, seq_len: int) -> int:
        """Linear kept-token schedule start_tokens → seq_len, quantized
        (reference random-LTD scheduler semantics). Returns seq_len exactly
        once the schedule completes, so 'finished' is reachable even when
        seq_len is not a multiple of difficulty_step."""
        c = self._ltd
        frac = min(1.0, step / max(1, c.total_steps))
        if frac >= 1.0:
            return seq_len
        r = int(c.start_tokens + (seq_len - c.start_tokens) * frac)
        r = r // c.difficulty_step * c.difficulty_step
        return max(min(r, seq_len), min(c.start_tokens, seq_len))

    def _apply_data_efficiency(self, batch: dict) -> dict:
        """Curriculum seqlen truncation (host-side, before global assembly —
        each new length is one extra compiled shape) + random-LTD kept-token
        schedule (a static jit argument: each quantum is one retrace)."""
        is_host = not isinstance(next(iter(batch.values())), jax.Array)
        seq = int(batch["input_ids"].shape[-1])
        if self.curriculum is not None and is_host:
            L = min(self.curriculum(self.global_steps), seq)
            batch = {k: (v[..., :L] if getattr(v, "ndim", 0) >= 2
                         and v.shape[-1] == seq else v)
                     for k, v in batch.items()}
            seq = L
        elif self.curriculum is not None and not self._warned_device_batch:
            self._warned_device_batch = True
            log_dist("curriculum_learning: batch arrived as pre-assembled "
                     "jax.Arrays — seqlen truncation only applies to host "
                     "batches; the curriculum is NOT in effect", ranks=[0])
        if self._ltd is not None:
            r = self._ltd_schedule_tokens(self.global_steps, seq)
            if r >= seq:
                r = 0          # schedule finished: full sequence again
            self._ltd_tokens = r
        return batch

    def _moq_eigenvalue(self) -> float:
        """Dominant Hessian eigenvalue of the current loss on the cached
        probe batch (the reference's pre-narrowing curvature check,
        engine.py:2116-2127). Few power iterations: MoQ needs the decay
        trend, not a tight estimate."""
        from ..utils.eigenvalue import max_eigenvalue

        params = jax.tree.map(lambda a: a.astype(jnp.float32),
                              self.state.master_params)
        probe = {k: jnp.asarray(v) for k, v in self._moq_probe_batch.items()}
        if jax.process_count() > 1:
            # the captured probe is host-local (one addressable shard per
            # process, different data on each): agree on process 0's copy
            # so every host schedules the same bit widths — divergent
            # comp_active tuples would desync the SPMD programs
            from jax.experimental import multihost_utils

            probe = multihost_utils.broadcast_one_to_all(probe)
        with self.mesh:
            eig, _ = max_eigenvalue(lambda p: self.model.loss(p, probe),
                                    params, iters=4)
        return float(eig)

    def _compiled_step(self, batch: dict):
        """AOT-lower/compile the step program that ``train_batch`` would
        run for this batch's shapes, WITHOUT executing it — nothing
        touches device memory, so configs that would OOM can be probed."""
        if not isinstance(next(iter(batch.values())), jax.Array):
            batch = self._make_global(batch)
        if self.offload:
            # offload engines: the device program is the grad step (the
            # update runs on the host) — its footprint IS the HBM question
            with self.mesh:
                return self._grad_step.lower(
                    self.compute_params, batch,
                    jax.ShapeDtypeStruct((), jnp.float32)).compile()
        comp_active = tuple(sorted(
            n for n, off in self._comp if self.global_steps >= off))
        if self._moq is not None and "weight_quantization" in comp_active:
            # mirror train_batch: compile the program that will actually
            # run (current scheduled bit-width), so the memory numbers
            # describe it and the cached executable is reusable
            comp_active = self._moq.annotate(comp_active)
        warm = (in_warmup(self.onebit, self.global_steps)
                if self.onebit is not None else False)
        with self.mesh:
            return self._train_step.lower(
                self.state, batch, max(0, self._ltd_tokens), comp_active,
                warm).compile()

    def compile_train_step(self, batch: dict) -> dict:
        """AOT-compile the train step and return the compiler's
        buffer-assignment summary (``*_size_in_bytes``). This is how
        memory levers are *measured* (bench_act_offload.py, autotuner
        feasibility): the numbers are the compiler's own."""
        from ..profiling.flops_profiler import compiled_memory_analysis

        return compiled_memory_analysis(self._compiled_step(batch))

    def cost_census(self, batch: dict) -> dict:
        """Per-program capacity census of the train step: static FLOPs /
        HBM bytes / collective bytes (compiler + HLO truth), joined with
        achieved ``train_step`` wall times from the span ring when spans
        are enabled — the training row of the capacity report
        (docs/OPERATIONS.md capacity-planning runbook). Backends without
        cost/memory analysis degrade to null-valued fields, never raise."""
        from ..observability.capacity import ProgramCensus, roofline_peaks

        pf, bw = roofline_peaks()
        census = ProgramCensus(peak_flops=pf, peak_bw=bw)
        census.measure("train_step", self._compiled_step(batch))
        if self.spans is not None:
            census.attach_spans(self.spans.events())
        return census.report()

    def grad_comm_summary(self) -> Optional[dict]:
        """Static wire summary of the gradient-communication spelling:
        mode, bucket plan, and exact payload bytes per step vs the fp32
        flat all-reduce it replaces (``comm.compressed.plan_wire_mbytes``).
        The ``achieved`` input of the capacity advisor's
        ``quantized_collectives`` lever; None when the explicit grad
        path is off (GSPMD owns the reduction — nothing to report)."""
        if not self.grad_comp or self._grad_plan is None:
            return None
        from ..comm.compressed import plan_wire_mbytes

        D = int(self.mesh.shape["data"])
        out = plan_wire_mbytes(self._grad_plan, D, self.grad_comp)
        # report the overlap the PLAN actually delivers, not the config
        # intent: bucket_elems larger than the tree degrades to one fused
        # bucket, which has nothing to overlap (the advisor's achieved
        # block must not claim otherwise)
        out.update({"active": True,
                    "overlap": bool(self.grad_overlap
                                    and len(self._grad_plan.buckets) > 1),
                    "overlap_requested": self.grad_overlap,
                    "error_feedback": bool(self._comm_err_shapes),
                    "data_world": D})
        return out

    def observe_device_stamps(self, step: int, stamps: dict) -> list:
        """Cross-host/device per-step completion stamps → the commscope
        straggler detector (observability/commscope.py). The seam a
        multi-host launcher feeds after gathering each process's stamp;
        single-process training feeds its own automatically. No-op
        (returns []) when the observatory is off."""
        if self.commscope is None:
            return []
        return self.commscope.observe_stamps(step, stamps)

    def comm_observatory(self, trace_source=None,
                         n_steps: Optional[int] = None,
                         path: Optional[str] = None) -> dict:
        """The communication observatory report: step anatomy (exposed
        vs overlapped collective time), the per-kind achieved
        bus-bandwidth ledger (static HLO bytes / measured trace wall),
        and the straggler snapshot — docs/OBSERVABILITY.md
        "Communication observatory".

        ``trace_source`` defaults to ``observability.trace_dir`` (the
        TraceWindow target); ``n_steps`` defaults to the configured
        ``trace_steps`` window length. On a backend whose profiler
        emits no device op timeline (CPU) every anatomy/ledger row
        degrades to nulls with one warning — never a raise."""
        if self.commscope is None:
            raise RuntimeError(
                "observability.commscope is not enabled — set "
                'observability.commscope={"enabled": true} (and '
                "trace_steps for the profiler window) to build the "
                "observatory")
        obs = self.config.observability
        if trace_source is None:
            trace_source = obs.trace_dir
        if self._hlo_by_kind is not None:
            self.commscope.set_collective_bytes(self._hlo_by_kind)
        if n_steps is None and obs.trace_steps:
            a, b = (int(s) for s in obs.trace_steps)
            n_steps = b - a + 1
        report = self.commscope.analyze(trace_source, n_steps=n_steps)
        # the quantized/overlapped grad-communication spelling, if on:
        # static wire bytes vs the fp32 equivalent — the capacity
        # advisor's quantized_collectives lever reads this as its
        # achieved block (score self-demotes to the REMAINING measured
        # exposed fraction)
        report["quantized"] = self.grad_comm_summary()
        if path:
            import json
            from pathlib import Path as _Path

            p = _Path(path)
            p.parent.mkdir(parents=True, exist_ok=True)
            tmp = p.with_name(p.name + ".tmp")
            tmp.write_text(json.dumps(report, indent=2, default=str),
                           encoding="utf-8")
            os.replace(tmp, p)
        return report

    # ----------------------------------------------------------- resilience
    def _note_bad_steps(self, bad: bool, window: int, last_loss: float) -> None:
        """Non-finite sentinel: ``bad`` covers ``window`` consecutive
        optimizer steps (1 on the offload path, ``steps_per_print`` on the
        in-device path). K consecutive bad steps halt with a typed error —
        a collapsed run (loss-scale death spiral, NaN weights) must stop
        burning budget, and the supervisor must see a *typed* cause."""
        if not self._max_bad_steps:
            return
        self._bad_step_streak = self._bad_step_streak + window if bad else 0
        if self._bad_step_streak >= self._max_bad_steps:
            from ..resilience.guards import NonFiniteLossError

            if self.flight is not None:
                # the halt is the post-mortem moment: freeze the black box
                # BEFORE unwinding so the dump shows the collapse window
                self.flight.note("nonfinite_halt",
                                 streak=self._bad_step_streak,
                                 last_loss=last_loss,
                                 step=self.global_steps)
                self.flight.dump("nonfinite_halt")
            raise NonFiniteLossError(
                f"halting: {self._bad_step_streak} consecutive bad optimizer "
                f"steps (threshold {self._max_bad_steps}) — non-finite loss "
                "or every step skipped on overflow; last loss "
                f"{last_loss!r} at global step {self.global_steps}. Resume "
                "from the last good checkpoint with a lower lr / higher "
                "initial loss scale.",
                streak=self._bad_step_streak, last_loss=last_loss)

    def _sentinel_at_boundary(self, loss: float) -> None:
        """In-device path: evaluate the sentinel from the report window's
        ``skipped_steps`` delta (the boundary already synced the state, so
        reading the counter adds no extra device wait)."""
        if not self._max_bad_steps:
            return
        window = int(self.config.steps_per_print)
        skipped_total = float(self.state.skipped_steps)
        all_skipped = (skipped_total - self._skipped_total_prev) >= window
        self._skipped_total_prev = skipped_total
        self._note_bad_steps(all_skipped or not math.isfinite(loss),
                             window, loss)

    # -------------------------------------------------------- observability
    def _record_step_metrics(self, metrics: dict, stats: Optional[dict],
                             extra_gauges: Optional[dict] = None) -> None:
        """Step metrics → the engine registry (Train/* + Memory/*)."""
        gauges = {"Train/loss": metrics["loss"], "Train/lr": metrics["lr"],
                  "Train/grad_norm": metrics["grad_norm"]}
        if "loss_scale" in metrics:
            gauges["Train/loss_scale"] = metrics["loss_scale"]
        if extra_gauges:
            gauges.update(extra_gauges)
        if stats:
            gauges["Train/samples_per_sec"] = stats["samples_per_sec"]
            for key in ("tflops", "mfu"):
                if key in stats:
                    gauges[f"Train/{key}"] = stats[key]
            self.metrics.histogram("Train/step_time_s").observe(
                stats["step_time_s"])
            if self._step_anomaly is not None \
                    and self._step_anomaly.observe(stats["step_time_s"]):
                self.metrics.counter("Train/step_time_regressions").inc()
                med, mad = self._step_anomaly.stats()
                self.metrics.gauge("Train/step_time_baseline_s").set(med)
                log_dist(
                    f"step-time regression: {stats['step_time_s']:.4f}s vs "
                    f"rolling median {med:.4f}s (MAD {mad:.4f}s) at step "
                    f"{self.global_steps}", ranks=[0], level="WARNING")
                if self.flight is not None:
                    self.flight.note("step_time_regression",
                                     step_s=stats["step_time_s"],
                                     median_s=med, mad_s=mad,
                                     step=self.global_steps)
        self.metrics.set_gauges(gauges)
        if metrics.get("skipped"):
            self.metrics.counter("Train/skipped_steps").inc(
                metrics["skipped"])
        if self.config.observability.hbm_watermark:
            from ..observability.xla import sample_memory

            # HBM watermark at the step boundary (one host call per report
            # window; zeros on backends that don't expose memory_stats)
            sample_memory(self.metrics, self.acc)

    def _emit_monitor_events(self, extra: Optional[list] = None) -> None:
        """Flush the registry (+ any hand-built events) through the monitor
        fan-out — CSV/TB/WandB and the JSONL/Prometheus sinks alike."""
        if not self.monitor:
            return
        events = self.metrics.to_events(self.global_steps)
        if extra:
            events.extend(extra)
        self.monitor.write_events(events)
        self.monitor.flush()

    def metrics_snapshot(self) -> dict:
        """Machine-readable view of the training registry (the serving
        analog lives on ``InferenceEngine.metrics_snapshot``)."""
        snap = self.metrics.snapshot()
        if self.goodput is not None:
            snap["goodput"] = self.goodput.snapshot()
        return snap

    def health(self) -> dict:
        """Liveness/readiness snapshot for the telemetry probes (the
        training analog of ``ServingEngine.health()``): a training
        process is ``ready`` while it can take steps — i.e. it hasn't
        halted on the non-finite sentinel (a halted engine only stays
        alive long enough for a post-mortem scrape)."""
        snap = self.metrics.snapshot()
        streak = getattr(self, "_bad_step_streak", 0)
        # getattr: the telemetry server starts inside _post_init, a few
        # lines before the resilience fields land — a probe racing
        # construction must degrade, not 500
        max_bad = getattr(self, "_max_bad_steps", 0)
        halted = bool(max_bad and streak >= max_bad)
        hist = snap["histograms"].get("Train/step_time_s", {})
        return {
            "state": "halted" if halted else "training",
            "ready": not halted,
            "global_steps": self.global_steps,
            "bad_step_streak": streak,
            "skipped_steps": int(
                snap["counters"].get("Train/skipped_steps", 0)),
            "last_step_s": hist.get("last"),
            "step_time_regressions": int(
                snap["counters"].get("Train/step_time_regressions", 0)),
        }

    def serve_telemetry(self, port: Optional[int] = None,
                        host: Optional[str] = None,
                        token: Optional[str] = None) -> int:
        """Start the live telemetry plane for the training process
        (``/metrics`` ``/healthz`` ``/readyz`` ``/goodput`` ``/flight``
        + token-gated ``POST /flight/dump``; the serving-only endpoints
        — ``/requests``, ``/drain``, ``/slo/reload`` — 404 cleanly).
        Returns the bound port; idempotent. Config gate:
        ``observability.telemetry = {"enabled": true, ...}``."""
        if self.telemetry is not None:
            return self.telemetry.port
        from ..observability.server import (TelemetryConfig, TelemetryHooks,
                                            TelemetryServer, flight_summary)

        tc = TelemetryConfig.from_any(self.config.observability.telemetry
                                      or None)
        host = host if host is not None else (
            tc.host if tc is not None else "127.0.0.1")
        port = port if port is not None else (tc.port if tc is not None
                                              else 0)
        token = token if token is not None else (
            tc.token if tc is not None else "")

        def refresh():
            if self.goodput is not None:
                self.goodput.export()

        hooks = TelemetryHooks(
            registry=self.metrics,
            step_fn=lambda: int(self.global_steps),
            refresh_fn=refresh,
            health_fn=self.health,
            goodput_fn=(self.goodput.export if self.goodput is not None
                        else None),
            flight_fn=((lambda: flight_summary(self.flight))
                       if self.flight is not None else None),
            dump_fn=((lambda: self.dump_flight("manual"))
                     if self.flight is not None else None))
        server = TelemetryServer(hooks, host=host, port=port, token=token)
        # bind FIRST: a failed bind must not leave a dead server object
        # that the idempotency guard then treats as running
        bound = server.start()
        self.telemetry = server
        return bound

    def dump_flight(self, reason: str = "manual"):
        """Freeze the flight recorder (observability/flight.py) now;
        None when no recorder is configured or the dump cap is reached."""
        if self.flight is None:
            return None
        return self.flight.dump(reason)

    def close(self) -> None:
        """Teardown: close any open XLA trace window, the telemetry
        server's listener thread, and the monitor's file handles. Safe
        to call more than once."""
        if self._trace_window is not None:
            self._trace_window.close()
        if self.telemetry is not None:
            self.telemetry.close()
            self.telemetry = None
        if self.monitor:
            self.monitor.close()

    def train_batch(self, batch: dict) -> dict:
        """One optimizer step over train_batch_size samples (micro-stepping,
        grad accumulation, and the update are all inside the compiled step;
        in offload mode the update runs on the host optimizer instead)."""
        gp = self.goodput
        if gp is None:
            return self._train_batch_impl(batch)
        # goodput attribution: the call window is productive step
        # dispatch (the first call — which builds the XLA program — is
        # the compile window); gaps between calls land in queue_empty
        # (data/host time) via the ledger's gap rule. Two clock reads.
        # Accounted on SUCCESS only: a first call that raises must not
        # flip the compiled-once flag (the retry pays the real compile
        # and must be attributed to it), and an aborted window reads as
        # idle gap rather than fake productive time.
        t0 = gp.clock()
        first = not self._gp_stepped
        out = self._train_batch_impl(batch)
        self._gp_stepped = True
        gp.on_train_step(t0, gp.clock(), compiled=first)
        return out

    def _train_batch_impl(self, batch: dict) -> dict:
        if self._abstract:
            raise RuntimeError(
                "engine was built with abstract_state=True (AOT probe "
                "mode): no state is materialized — only compile_train_step "
                "is available")
        if self._chaos_preempt is not None \
                and self.global_steps == self._chaos_preempt:
            from ..resilience import chaos as _chaos

            _chaos.deliver_preemption()
        self._check_flops_nominal(batch)
        if self._trace_window is not None:
            # windowed XLA capture: opens entering trace_steps[0], closes
            # after trace_steps[1] completes (observability/xla.py)
            self._trace_window.on_step(self.global_steps)
        if self.offload:
            return self._train_batch_offload(batch)
        wcb = self.config.wall_clock_breakdown
        # one shared step-window clock for spans AND the comm
        # observatory (commscope reuses the spans clock when both are
        # on, so their windows agree to the exact float)
        _step_clk = (self.spans.clock if self.spans is not None else
                     (self.commscope.clock if self.commscope is not None
                      else None))
        t_step0 = _step_clk() if _step_clk is not None else 0.0
        self.throughput.start()
        if wcb:
            self.timers.start("batch_prep")
        if self.curriculum is not None or self._ltd is not None:
            batch = self._apply_data_efficiency(batch)
        if not isinstance(next(iter(batch.values())), jax.Array):
            batch = self._make_global(batch)
        if wcb:
            self.timers.stop("batch_prep")
        if self._moq is not None and self._moq_probe_batch is None:
            # small fixed probe batch for the curvature power iteration:
            # captured AFTER globalization (pre-converted jax batches
            # arrive in the (gas, batch, ...) layout — flatten it), one
            # row per data shard (the trunk's batch constraint needs
            # dp-divisibility)
            from ..models.transformer import mesh_dp_world

            rows = max(1, mesh_dp_world(self.mesh))

            def probe_rows(v):
                # read only host-local shards: np.asarray on a globalized
                # array raises on a multi-process mesh (remote shards)
                if isinstance(v, jax.Array) and not v.is_fully_addressable:
                    a = np.asarray(v.addressable_shards[0].data)
                else:
                    a = np.asarray(v)
                if a.ndim >= 2:
                    a = a.reshape((-1,) + a.shape[2:])
                if len(a) < rows:        # tiny shard: tile up to dp rows
                    a = np.resize(a, (rows,) + a.shape[1:])
                return a[:rows]

            self._moq_probe_batch = {k: probe_rows(v)
                                     for k, v in batch.items()}
        comp_active = tuple(sorted(
            n for n, off in self._comp if self.global_steps >= off))
        if self._moq is not None and "weight_quantization" in comp_active:
            self._moq.maybe_step(self.global_steps, self._moq_eigenvalue)
            comp_active = self._moq.annotate(comp_active)
        warm = (in_warmup(self.onebit, self.global_steps)
                if self.onebit is not None else False)
        if wcb:
            self.timers.start("step_dispatch")
        with self.mesh:
            self.state, metrics = self._train_step(
                self.state, batch, max(0, self._ltd_tokens), comp_active, warm)
        if wcb:
            self.timers.stop("step_dispatch")
        self.global_steps += 1
        boundary = self.global_steps % self.config.steps_per_print == 0
        if wcb or boundary:
            # sync FIRST, then floatify: float() on the metrics arrays is
            # itself a device wait, and running it before the step_sync
            # timer would bury the whole device-execution time in no timer
            if wcb:
                self.timers.start("step_sync")
            jax.block_until_ready(self.state.step)
            if wcb:
                self.timers.stop("step_sync")
            metrics = {k: float(v) for k, v in metrics.items()}
            stats = self.throughput.stop(report=True)
            if wcb:
                # wall-clock breakdown → registry gauges (log() also prints
                # the reference-style "time (ms)" line and resets). Gauges
                # record per step; sinks still flush only at boundaries.
                for name, ms in self.timers.log(reset=True).items():
                    self.metrics.gauge(f"Train/time_{name}_ms").set(ms)
            if boundary:
                self._sentinel_at_boundary(metrics["loss"])
                log_dist(f"step={self.global_steps} loss={metrics['loss']:.4f} "
                         f"lr={metrics['lr']:.3e} gnorm={metrics['grad_norm']:.3f}",
                         ranks=[0])
                # recording + emission stay on the report cadence even
                # under wall_clock_breakdown (the HBM watermark and sink
                # flush are documented as per-boundary, never per-step)
                self._record_step_metrics(metrics, stats)
                extra = []
                if self._moq is not None and any(
                        n.startswith("weight_quantization")
                        for n in comp_active):
                    # observability for the quantization schedule (the
                    # reference logs its quantizer's bit switches too);
                    # only while QAT is actually active per its offset
                    extra.append(("Train/moq_bits", self._moq.bits,
                                  self.global_steps))
                self._emit_monitor_events(extra)
        else:
            self.throughput.stop(report=False)
        if _step_clk is not None:
            t_step1 = _step_clk()
            if self.spans is not None:
                self.spans.emit(TRAIN_STEP, t_step0, t_step1,
                                step=self.global_steps)
                if wcb:
                    # re-emit the wall-clock-breakdown timer windows as
                    # phase spans (last completed interval per timer; no
                    # new clocks)
                    for name in ("batch_prep", "step_dispatch",
                                 "step_sync"):
                        tm = self.timers(name)
                        if tm.last_stop > 0:
                            self.spans.emit(TRAIN_PHASE, tm.last_start,
                                            tm.last_stop,
                                            step=self.global_steps,
                                            phase=name)
            if self.commscope is not None:
                # per-step host window + this process's completion stamp
                # (multi-host launchers gather and feed cross-host stamps
                # through observe_device_stamps; a lone process's single
                # stamp leaves the straggler detector honestly inert).
                # traced= marks steps inside the TraceWindow so the
                # Perfetto rebase anchors the capture to THEM, not to
                # whatever pre-window steps were also stamped
                self.commscope.on_step(
                    self.global_steps, t_step0, t_step1,
                    traced=(self._trace_window is not None
                            and self._trace_window.active))
                self.commscope.observe_stamps(
                    self.global_steps, {jax.process_index(): t_step1})
        # Profiler fires OUTSIDE the throughput window (its extra timed step
        # + one-time AOT compile must not pollute samples/s accounting).
        if self.flops_profiler and self.flops_profiler.should_fire():
            self.flops_profiler.profile(batch)
        if not self._comms_logged:
            # comms_logger: count the GSPMD-inserted collectives from the
            # compiled HLO once (the Python ledger only sees explicit comm.*
            # wrappers), plus the ledger summary. NOTE: the AOT
            # lower().compile() duplicates the step compile once — an
            # accepted, opt-in diagnostics cost (post-optimization HLO is
            # the only place the inserted collectives exist).
            self._comms_logged = True
            try:
                from ..comm.hlo_analysis import collective_summary

                with self.mesh:
                    compiled = self._train_step.lower(
                        self.state, batch, max(0, self._ltd_tokens),
                        comp_active, warm).compile()
                summ = collective_summary(compiled)
                # static per-step wire bytes by kind: kept for the
                # commscope ledger join (comm_observatory) — the
                # achieved-bandwidth denominator comes from the trace,
                # the numerator from here
                self._hlo_by_kind = summ
                if self.commscope is not None:
                    self.commscope.set_collective_bytes(summ)
                for key, d in sorted(summ.items()):
                    log_dist(f"comms | HLO {key}: n={int(d['count'])} "
                             f"vol={d['mbytes']:.1f} MB", ranks=[0])
                    # collective census → Comm/* gauges: per-step wire
                    # bytes by kind, exact from the compiled program
                    self.metrics.set_gauges({
                        f"Comm/hlo/{key}/count": d["count"],
                        f"Comm/hlo/{key}/mbytes": d["mbytes"]})
                if self.config.comms_logger.enabled:
                    from ..comm.comm import comms_logger as _cl

                    for name, value, _ in _cl.as_monitor_events(
                            self.global_steps):
                        self.metrics.gauge(name).set(value)
                    _cl.log_summary()
                # no emit here: the Comm/* gauges ride the next report
                # boundary's flush (an emit now would duplicate this
                # step's Train/* rows in every sink)
            except Exception as e:   # best-effort per backend
                log_dist(f"comms_logger: HLO summary unavailable ({e})")
        return metrics

    def eval_batch(self, batch: dict) -> float:
        if not isinstance(next(iter(batch.values())), jax.Array):
            batch = self._make_global(batch, gas_dim=False)
        with self.mesh:
            if self.offload:
                return float(self._eval_offload(self.compute_params, batch))
            return float(self._eval_step(self.state.master_params, batch))

    @property
    def lr(self) -> float:
        step = (jnp.int32(self.global_steps) if self.offload
                else self.state.step)
        return float(self.lr_schedule(step))

    @property
    def train_micro_batch_size_per_device(self) -> int:
        return int(self.config.train_micro_batch_size_per_gpu)

    @property
    def train_batch_size(self) -> int:
        return int(self.config.train_batch_size)

    # ------------------------------------------------------------ checkpoint
    def save_checkpoint(self, save_dir: str, tag: str | None = None) -> str:
        from .checkpoint.engine import save_checkpoint as _save

        if self.config.elasticity.enabled:
            # cross-restart immutability of the elastic schema (reference
            # elasticity.py:208): fingerprint lives next to the checkpoints
            from ..elasticity import assert_elastic_config_consistent

            assert_elastic_config_consistent(self.config.elasticity, save_dir)
        if self.goodput is not None:
            # checkpoint commit is honest badput: time the save window
            # into its own bucket instead of letting it read as idle
            with self.goodput.window("checkpoint"):
                return _save(self, save_dir, tag)
        return _save(self, save_dir, tag)

    def load_checkpoint(self, load_dir: str, tag: str | None = None) -> str:
        from .checkpoint.engine import load_checkpoint as _load

        if self.config.elasticity.enabled:
            from ..elasticity import assert_elastic_config_consistent

            assert_elastic_config_consistent(self.config.elasticity, load_dir)
        return _load(self, load_dir, tag)

    def wait_for_checkpoint(self) -> None:
        """Block until an async checkpoint save has committed to disk."""
        from .checkpoint.engine import wait_for_checkpoint as _wait

        _wait(self)

    # ------------------------------------------------------------- profiling
    def start_profile_trace(self, logdir: str) -> None:
        """Begin an XLA profiler trace (the NVTX/nsys analog —
        SURVEY §5 tracing: xplane → tensorboard/perfetto). Wrap some
        train_batch calls and view with `tensorboard --logdir`."""
        jax.profiler.start_trace(logdir)
        log_dist(f"profiler trace started → {logdir}", ranks=[0])

    def stop_profile_trace(self) -> None:
        # drain outstanding async-dispatched steps first, or the trace
        # closes mid-step and drops the device activity being profiled
        jax.block_until_ready(self.compute_params if self.offload
                              else self.state)
        jax.profiler.stop_trace()
        log_dist("profiler trace stopped", ranks=[0])


def initialize(config: Config | dict | str | None = None, model=None,
               mesh: Optional[Mesh] = None, seed: Optional[int] = None,
               **kwargs) -> Engine:
    """Public entry point (reference ``deepspeed.initialize``,
    ``deepspeed/__init__.py:64``). Returns the engine; the optimizer and LR
    scheduler live inside it, built from the config."""
    assert model is not None, "initialize() requires a model"
    return Engine(config, model, mesh=mesh, seed=seed, **kwargs)
