"""Native optimizers.

Capability analog of the reference's fused/CPU optimizers
(``csrc/adam/multi_tensor_adam.cu``, ``csrc/adam/cpu_adam_impl.cpp``,
``csrc/lamb/fused_lamb_cuda_kernel.cu``, ``csrc/lion/*``,
``csrc/adagrad/cpu_adagrad.cpp``, and the Python wrappers in
``deepspeed/ops/adam|lamb|lion|adagrad``). On TPU the multi-tensor-apply
machinery is unnecessary — the whole update is one XLA program fused across
the parameter pytree — so each optimizer is a pure ``update`` rule over fp32
master state. The update runs shard-wise on ZeRO-partitioned state; XLA emits
zero collectives for it because every operand shares the master sharding.

States are kept as explicit pytrees so ZeRO partitioning, offload, and
universal checkpointing can address them per-leaf, mirroring how the
reference checkpoints ``exp_avg``/``exp_avg_sq`` per partition.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class OptState(NamedTuple):
    """Moment state; unused slots are empty pytrees to keep one step signature."""

    mu: Any    # first moment / momentum / Adagrad accumulator
    nu: Any    # second moment
    count: jnp.ndarray  # int32 step counter (bias correction)


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """An optimizer = init + shard-wise update on fp32 master params."""

    name: str
    init: Callable[[Any], OptState]
    update: Callable[[Any, OptState, Any, jnp.ndarray], tuple[Any, OptState]]
    hyperparams: dict = dataclasses.field(default_factory=dict)


def _zeros_like_tree(params):
    return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)


def _empty_tree(params):
    return jax.tree.map(lambda p: jnp.zeros((0,), jnp.float32), params)


# ------------------------------------------------------------------- Adam(W)
def adam(lr_placeholder: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
         weight_decay: float = 0.0, adamw: bool = True, bias_correction: bool = True):
    b1, b2 = betas

    def init(params) -> OptState:
        return OptState(mu=_zeros_like_tree(params), nu=_zeros_like_tree(params),
                        count=jnp.zeros((), jnp.int32))

    def update(params, state: OptState, grads, lr):
        count = state.count + 1
        c = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - b1 ** c
            bc2 = 1.0 - b2 ** c
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(p, m, v, g):
            g = g.astype(jnp.float32)
            if weight_decay and not adamw:  # classic Adam: L2 folded into grad
                g = g + weight_decay * p
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * jnp.square(g)
            step = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay and adamw:  # AdamW: decoupled decay
                step = step + weight_decay * p
            return p - lr * step, m, v

        flat_p, treedef = jax.tree.flatten(params)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_g = treedef.flatten_up_to(grads)
        out = [leaf(p, m, v, g) for p, m, v, g in zip(flat_p, flat_m, flat_v, flat_g)]
        new_p = treedef.unflatten([o[0] for o in out])
        new_m = treedef.unflatten([o[1] for o in out])
        new_v = treedef.unflatten([o[2] for o in out])
        return new_p, OptState(mu=new_m, nu=new_v, count=count)

    return Optimizer("adamw" if adamw else "adam", init, update,
                     dict(betas=betas, eps=eps, weight_decay=weight_decay))


# --------------------------------------------------------------------- Lion
def lion(betas=(0.9, 0.99), weight_decay: float = 0.0):
    b1, b2 = betas

    def init(params) -> OptState:
        return OptState(mu=_zeros_like_tree(params), nu=_empty_tree(params),
                        count=jnp.zeros((), jnp.int32))

    def update(params, state: OptState, grads, lr):
        def leaf(p, m, g):
            g = g.astype(jnp.float32)
            upd = jnp.sign(b1 * m + (1.0 - b1) * g)
            if weight_decay:
                upd = upd + weight_decay * p
            new_m = b2 * m + (1.0 - b2) * g
            return p - lr * upd, new_m

        new = jax.tree.map(leaf, params, state.mu, grads)
        new_p = jax.tree.map(lambda t: t[0], new, is_leaf=lambda x: isinstance(x, tuple))
        new_m = jax.tree.map(lambda t: t[1], new, is_leaf=lambda x: isinstance(x, tuple))
        return new_p, OptState(mu=new_m, nu=state.nu, count=state.count + 1)

    return Optimizer("lion", init, update, dict(betas=betas, weight_decay=weight_decay))


# --------------------------------------------------------------------- LAMB
def lamb(betas=(0.9, 0.999), eps: float = 1e-6, weight_decay: float = 0.0,
         min_trust: float = 0.01, max_trust: float = 10.0):
    """LAMB with per-param trust ratio (reference ``fused_lamb_cuda_kernel.cu``).

    Norms are global over each (possibly data-sharded) master param; XLA
    reduces them across shards automatically.
    """
    b1, b2 = betas

    def init(params) -> OptState:
        return OptState(mu=_zeros_like_tree(params), nu=_zeros_like_tree(params),
                        count=jnp.zeros((), jnp.int32))

    def update(params, state: OptState, grads, lr):
        count = state.count + 1
        c = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** c
        bc2 = 1.0 - b2 ** c

        def leaf(p, m, v, g):
            g = g.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * jnp.square(g)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + weight_decay * p
            w_norm = jnp.linalg.norm(p.reshape(-1))
            u_norm = jnp.linalg.norm(u.reshape(-1))
            trust = jnp.where((w_norm > 0) & (u_norm > 0),
                              jnp.clip(w_norm / u_norm, min_trust, max_trust), 1.0)
            return p - lr * trust * u, m, v

        flat_p, treedef = jax.tree.flatten(params)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_g = treedef.flatten_up_to(grads)
        out = [leaf(p, m, v, g) for p, m, v, g in zip(flat_p, flat_m, flat_v, flat_g)]
        return (treedef.unflatten([o[0] for o in out]),
                OptState(mu=treedef.unflatten([o[1] for o in out]),
                         nu=treedef.unflatten([o[2] for o in out]), count=count))

    return Optimizer("lamb", init, update, dict(betas=betas, eps=eps,
                                                weight_decay=weight_decay))


# ------------------------------------------------------------------ Adagrad
def adagrad(eps: float = 1e-10, weight_decay: float = 0.0):
    def init(params) -> OptState:
        return OptState(mu=_zeros_like_tree(params), nu=_empty_tree(params),
                        count=jnp.zeros((), jnp.int32))

    def update(params, state: OptState, grads, lr):
        def leaf(p, acc, g):
            g = g.astype(jnp.float32)
            if weight_decay:
                g = g + weight_decay * p
            acc = acc + jnp.square(g)
            return p - lr * g / (jnp.sqrt(acc) + eps), acc

        new = jax.tree.map(leaf, params, state.mu, grads)
        new_p = jax.tree.map(lambda t: t[0], new, is_leaf=lambda x: isinstance(x, tuple))
        new_a = jax.tree.map(lambda t: t[1], new, is_leaf=lambda x: isinstance(x, tuple))
        return new_p, OptState(mu=new_a, nu=state.nu, count=state.count + 1)

    return Optimizer("adagrad", init, update, dict(eps=eps, weight_decay=weight_decay))


# ---------------------------------------------------------------------- SGD
def sgd(momentum: float = 0.0, weight_decay: float = 0.0, nesterov: bool = False):
    def init(params) -> OptState:
        mu = _zeros_like_tree(params) if momentum else _empty_tree(params)
        return OptState(mu=mu, nu=_empty_tree(params), count=jnp.zeros((), jnp.int32))

    def update(params, state: OptState, grads, lr):
        def leaf(p, m, g):
            g = g.astype(jnp.float32)
            if weight_decay:
                g = g + weight_decay * p
            if momentum:
                m = momentum * m + g
                g = g + momentum * m if nesterov else m
            return p - lr * g, m

        new = jax.tree.map(leaf, params, state.mu, grads)
        new_p = jax.tree.map(lambda t: t[0], new, is_leaf=lambda x: isinstance(x, tuple))
        new_m = jax.tree.map(lambda t: t[1], new, is_leaf=lambda x: isinstance(x, tuple))
        return new_p, OptState(mu=new_m, nu=state.nu, count=state.count + 1)

    return Optimizer("sgd", init, update, dict(momentum=momentum))


# ------------------------------------------------------------------ registry
def build_optimizer(opt_type: str, params: dict) -> Optimizer:
    """ds_config ``optimizer.type`` → optimizer (reference
    ``engine._configure_basic_optimizer`` name dispatch, ``engine.py:1239``)."""
    t = opt_type.lower().replace("_", "")
    p = dict(params)
    lr = p.pop("lr", None)  # lr flows through the scheduler, not the optimizer
    betas = tuple(p.pop("betas", (0.9, 0.999)))
    wd = p.pop("weight_decay", 0.0)
    eps = p.pop("eps", 1e-8)
    p.pop("torch_adam", None), p.pop("adam_w_mode", None), p.pop("freeze_step", None)
    p.pop("cuda_aware", None), p.pop("comm_backend_name", None)
    if t in ("adam",):
        return adam(betas=betas, eps=eps, weight_decay=wd, adamw=False)
    if t in ("adamw", "fusedadam", "cpuadam"):
        return adam(betas=betas, eps=eps, weight_decay=wd, adamw=True)
    # (1-bit optimizer names never reach here: the engine intercepts
    # ONEBIT_TYPES and drives runtime/onebit.py's momentum-compressed step.)
    if t in ("lamb", "fusedlamb"):
        return lamb(betas=(betas[0], betas[1]), eps=eps, weight_decay=wd)
    if t in ("lion", "fusedlion", "cpulion"):
        return lion(betas=(betas[0], betas[1]) if betas else (0.9, 0.99), weight_decay=wd)
    if t in ("adagrad", "cpuadagrad"):
        return adagrad(eps=p.pop("eps", 1e-10) if "eps" in p else 1e-10, weight_decay=wd)
    if t == "sgd":
        return sgd(momentum=p.pop("momentum", 0.0), weight_decay=wd,
                   nesterov=p.pop("nesterov", False))
    raise ValueError(f"unknown optimizer type '{opt_type}'")
