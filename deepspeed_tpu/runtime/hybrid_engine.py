"""Hybrid engine: one set of params served by both train and decode steps.

Reference: ``DeepSpeedHybridEngine`` (``runtime/hybrid_engine.py:32``) flips
a ZeRO-3 training module into inference mode for RLHF ``generate()`` —
gathering params, fusing LoRA, swapping in inference containers, retaking
KV-cache workspace, then unwinding all of it for the next training step.

TPU-native: training state and the decode loop are just two jitted functions
over the same sharded master params — ``generate`` casts the engine's
current master params to the compute dtype (the same cast the train step
performs) and runs the shared KV-cache decode loop from
:mod:`deepspeed_tpu.inference.decode`. No containers, no LoRA fuse/unfuse,
no workspace retaking.
"""

from __future__ import annotations

from collections import OrderedDict
from functools import partial
from typing import Optional

import jax

from ..inference.decode import generate_tokens
from ..inference.engine import _MAX_COMPILED_SHAPES, model_with_dtype
from ..inference.sampling import sample_logits
from .engine import Engine


class HybridEngine(Engine):
    """Training engine + in-place generation over the live params."""

    def __init__(self, *args, eos_token_id: Optional[int] = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.eos_token_id = eos_token_id
        self._gen_cache: OrderedDict = OrderedDict()
        self._rng = jax.random.PRNGKey(self.seed)

    def _generate_impl(self, master_params, input_ids, rng, *, max_new: int,
                       temperature: float, top_k: int, top_p: float,
                       greedy: bool):
        params = self._cast_compute(master_params)
        model = model_with_dtype(self.model, self.compute_dtype)
        sampler = partial(sample_logits, temperature=temperature, top_k=top_k,
                          top_p=top_p, greedy=greedy)
        return generate_tokens(model, params, input_ids, rng,
                               max_new=max_new, sampler=sampler,
                               eos_token_id=self.eos_token_id,
                               cache_dtype=self.compute_dtype)

    def generate(self, input_ids, max_new_tokens: int = 64, *,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 greedy: bool = False, rng: Optional[jax.Array] = None):
        """Sample continuations from the CURRENT training params — the RLHF
        actor rollout step (reference ``hybrid_engine.py:174``). Sampled
        calls draw from a persistent PRNG stream so repeated rollouts
        differ; pass ``rng`` for reproducibility."""
        import jax.numpy as jnp

        input_ids = jnp.asarray(input_ids, jnp.int32)
        key = (input_ids.shape, int(max_new_tokens), float(temperature),
               int(top_k), float(top_p), bool(greedy))
        fn = self._gen_cache.get(key)
        if fn is None:
            fn = jax.jit(partial(
                self._generate_impl, max_new=int(max_new_tokens),
                temperature=temperature, top_k=top_k, top_p=top_p,
                greedy=greedy))
            self._gen_cache[key] = fn
            if len(self._gen_cache) > _MAX_COMPILED_SHAPES:
                self._gen_cache.popitem(last=False)
        else:
            self._gen_cache.move_to_end(key)
        if rng is None:
            self._rng, rng = jax.random.split(self._rng)
        with self.mesh:
            return fn(self.state.master_params, input_ids, rng)
