"""Hybrid engine: one set of params served by both train and decode steps.

Reference: ``DeepSpeedHybridEngine`` (``runtime/hybrid_engine.py:32``) flips
a ZeRO-3 training module into inference mode for RLHF ``generate()`` —
gathering params, fusing LoRA, swapping in inference containers, retaking
KV-cache workspace, then unwinding all of it for the next training step.

TPU-native: training state and the decode loop are just two jitted functions
over the same sharded master params — ``generate`` casts the engine's
current master params to the compute dtype (the same cast the train step
performs) and runs the shared KV-cache decode loop from
:mod:`deepspeed_tpu.inference.decode`. No containers, no LoRA fuse/unfuse,
no workspace retaking.
"""

from __future__ import annotations

from collections import OrderedDict
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..inference.decode import generate_tokens
from ..inference.engine import _MAX_COMPILED_SHAPES, model_with_dtype
from ..inference.sampling import sample_logits
from .engine import Engine


def _gather_logp(logits, ids):
    """Per-token log p(ids) under logits: (B, T, V), (B, T) → (B, T) f32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, ids[..., None], axis=-1)[..., 0]


def ppo_token_loss(logp, old_logp, advantage, mask, *,
                   clip_eps: float = 0.2, kl_coef: float = 0.1):
    """Clipped policy-ratio objective + KL penalty (the PPO-shaped loss of
    DeepSpeed-Chat's actor step, ``blogs/deepspeed-chat/README.md:41``).

    logp/old_logp/mask: (B, T) over predicted positions; advantage: (B,)
    or (B, T). Returns a scalar to MINIMIZE."""
    adv = advantage if advantage.ndim == logp.ndim else advantage[:, None]
    log_ratio = logp - old_logp
    ratio = jnp.exp(log_ratio)
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps) * adv
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    pg = -jnp.sum(jnp.minimum(unclipped, clipped) * mask) / denom
    # k3 KL estimator (Schulman): exp(-x) + x - 1 >= 0 pointwise, so the
    # penalty is a true deviation cost in BOTH directions (the signed k1
    # estimator would *reward* one-sided logp increases)
    kl = jnp.sum((jnp.exp(-log_ratio) + log_ratio - 1.0) * mask) / denom
    return pg + kl_coef * kl


class _RLHFLossMixin:
    """Routes batches that carry PPO keys (``ppo_old_logp``,
    ``ppo_advantage``) through the clipped-ratio objective; plain LM
    batches fall through to the standard loss unchanged."""

    ppo_clip_eps: float = 0.2
    ppo_kl_coef: float = 0.1

    def loss(self, params, batch, *, remat_policy=None, **kw):
        if "ppo_old_logp" not in batch:
            return super().loss(params, batch, remat_policy=remat_policy,
                                **kw)
        ids = batch["input_ids"]
        logits = self.apply(params, ids,
                            attn_mask=batch.get("attention_mask"),
                            remat_policy=remat_policy)
        logp = _gather_logp(logits[:, :-1], ids[:, 1:])
        mask = batch.get("loss_mask")
        mask = (mask[:, 1:].astype(jnp.float32) if mask is not None
                else jnp.ones_like(logp))
        return ppo_token_loss(logp, batch["ppo_old_logp"],
                              batch["ppo_advantage"], mask,
                              clip_eps=self.ppo_clip_eps,
                              kl_coef=self.ppo_kl_coef)


def _convert_rlhf(model):
    cls = type(model)
    new_cls = type(f"RLHF{cls.__name__}", (_RLHFLossMixin, cls), {})
    new = object.__new__(new_cls)
    new.__dict__.update(model.__dict__)
    return new


class HybridEngine(Engine):
    """Training engine + in-place generation over the live params."""

    def __init__(self, config=None, model=None, *args,
                 eos_token_id: Optional[int] = None, **kwargs):
        super().__init__(config, _convert_rlhf(model), *args, **kwargs)
        self.eos_token_id = eos_token_id
        self._gen_cache: OrderedDict = OrderedDict()
        self._logp_cache: OrderedDict = OrderedDict()
        self._rng = jax.random.PRNGKey(self.seed)

    def _serving_params(self, master_params):
        """Compute-cast params with LoRA adapters MERGED — the reference
        hybrid engine's fuse-before-generate
        (``containers/features/hybrid_engine.py:12``), here one functional
        transform instead of module surgery (and nothing to unfuse)."""
        params = self._cast_compute(master_params)
        if hasattr(self.model, "merge_lora"):
            params = self.model.merge_lora(params)
        return params

    def _generate_impl(self, master_params, input_ids, rng, *, max_new: int,
                       temperature: float, top_k: int, top_p: float,
                       greedy: bool):
        params = self._serving_params(master_params)
        model = model_with_dtype(self.model, self.compute_dtype)
        sampler = partial(sample_logits, temperature=temperature, top_k=top_k,
                          top_p=top_p, greedy=greedy)
        return generate_tokens(model, params, input_ids, rng,
                               max_new=max_new, sampler=sampler,
                               eos_token_id=self.eos_token_id,
                               cache_dtype=self.compute_dtype)

    def token_logprobs(self, input_ids) -> jax.Array:
        """(B, S) ids → (B, S-1) fp32 log-probs of each realized next token
        under the CURRENT policy — the rollout-time ``old_logp`` snapshot
        of the PPO loop."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        fn = self._logp_cache.get(input_ids.shape)
        if fn is None:
            def impl(master, ids):
                # _serving_params already merged any adapters; the LoRA
                # wrapper's own merge no-ops on a merged tree
                params = self._serving_params(master)
                model = model_with_dtype(self.model, self.compute_dtype)
                logits = model.apply(params, ids)
                return _gather_logp(logits[:, :-1], ids[:, 1:])

            fn = jax.jit(impl)
            self._logp_cache[input_ids.shape] = fn
            if len(self._logp_cache) > _MAX_COMPILED_SHAPES:
                self._logp_cache.popitem(last=False)
        with self.mesh:
            return fn(self.state.master_params, input_ids)

    def generate(self, input_ids, max_new_tokens: int = 64, *,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 greedy: bool = False, rng: Optional[jax.Array] = None):
        """Sample continuations from the CURRENT training params — the RLHF
        actor rollout step (reference ``hybrid_engine.py:174``). Sampled
        calls draw from a persistent PRNG stream so repeated rollouts
        differ; pass ``rng`` for reproducibility."""
        import jax.numpy as jnp

        input_ids = jnp.asarray(input_ids, jnp.int32)
        key = (input_ids.shape, int(max_new_tokens), float(temperature),
               int(top_k), float(top_p), bool(greedy))
        fn = self._gen_cache.get(key)
        if fn is None:
            fn = jax.jit(partial(
                self._generate_impl, max_new=int(max_new_tokens),
                temperature=temperature, top_k=top_k, top_p=top_p,
                greedy=greedy))
            self._gen_cache[key] = fn
            if len(self._gen_cache) > _MAX_COMPILED_SHAPES:
                self._gen_cache.popitem(last=False)
        else:
            self._gen_cache.move_to_end(key)
        if rng is None:
            self._rng, rng = jax.random.split(self._rng)
        with self.mesh:
            return fn(self.state.master_params, input_ids, rng)
