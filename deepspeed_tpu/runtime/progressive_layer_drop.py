"""Progressive layer drop (PLD): scheduled stochastic depth.

Analog of the reference's ``runtime/progressive_layer_drop.py:40`` + its
engine hook (``engine.py:1786``): the keep probability
``theta(t) = (1 - theta_min)·exp(-gamma·t) + theta_min`` decays from 1
toward ``theta_min`` over training, and deeper layers drop more aggressively
(``p_l = 1 - (l/L)·(1 - theta)``, the PLD paper's depth scaling).  Dropped
layers are skipped with ``lax.cond`` — the compute is actually saved at run
time, not masked out.

The step enters as a TRACED scalar (``pld_step`` attr set by the engine from
``state.step`` inside the jitted step), so the schedule is continuous — no
retrace per step. Eval leaves ``pld_step`` None → all layers run.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pipe_stage_layer_offset(n_local_layers: int) -> jnp.ndarray:
    """Global index of this pipeline stage's first layer (0 when no pipe
    axis is bound — the dense trunk). Factored out so the global-depth
    rule's wiring is directly testable: if this silently returned 0 under
    a pipe axis, PLD would regress to per-stage depth scaling, the exact
    bug the old engine-level exclusion guarded against."""
    try:
        return (lax.axis_index("pipe") * n_local_layers).astype(jnp.float32)
    except NameError:
        # NameError is what jax==0.9.0 raises for an unbound axis name
        # (a JAX internal, not API — test_aux.py::test_unbound_axis_raises
        # pins it; re-check on any JAX bump, docs/OPERATIONS.md). Keeping
        # the catch NARROW matters: a broader except would silently turn a
        # real error into offset 0 — the per-stage depth regression this
        # helper exists to prevent.
        return jnp.float32(0.0)


class PLDMixin:
    pld_theta_min: float = 0.5
    pld_gamma: float = 0.001
    pld_seed: int = 23
    pld_step = None            # traced scalar during the train trace

    def set_pld_step(self, step) -> None:
        self.pld_step = step

    def _scan_layers(self, x, layers, positions, attn_mask, remat_policy):
        if self.pld_step is None:
            return super()._scan_layers(x, layers, positions, attn_mask,
                                        remat_policy)
        from ..platform.mesh import current_mesh, manual_axes_of
        mesh = current_mesh()
        if (mesh is not None and not mesh.empty
                and int(mesh.shape.get("pipe", 1)) != 1
                and "pipe" not in manual_axes_of(mesh)):
            # A pipe-sharded mesh whose pipe axis is NOT manual means this
            # trunk is running outside the pipeline engine's shard_map:
            # axis_index("pipe") is unbound, the stage offset silently
            # becomes 0, and PLD regresses to per-stage depth scaling.
            # Fail loud instead (advisor r3).
            raise ValueError(
                "PLD under a pipe-sharded mesh requires the pipeline "
                "engine (manual pipe axis); running the dense trunk here "
                "would silently drop the global-depth stage offset")
        L_local = jax.tree.leaves(layers)[0].shape[0]
        # Under pipeline parallelism this method sees only the stage-local
        # layer slice; the PLD depth scaling is defined over the GLOBAL
        # depth (paper's p_l = 1 - (l/L)(1-theta)), so recover the global
        # index as stage*L_local + local. axis_index raises at trace time
        # when no pipe axis is bound (dense trunk) — offset 0 there.
        L = getattr(self.cfg, "n_layer", L_local)
        offset = pipe_stage_layer_offset(L_local)
        t = self.pld_step.astype(jnp.float32)
        theta = ((1.0 - self.pld_theta_min) * jnp.exp(-self.pld_gamma * t)
                 + self.pld_theta_min)
        # key entropy: the STEP drives per-step variation (activations alone
        # are constant for, e.g., fixed-BOS data — the drop pattern would
        # freeze and starve the same deep layers all run)
        bits = lax.bitcast_convert_type(x[0, 0].astype(jnp.float32), jnp.int32)
        key = jax.random.fold_in(jax.random.PRNGKey(self.pld_seed),
                                 self.pld_step.astype(jnp.int32))
        key = jax.random.fold_in(key, jnp.sum(bits, dtype=jnp.int32)
                                 & 0x7fffffff)

        body = self._layer
        if remat_policy is not None:
            body = jax.checkpoint(self._layer, policy=remat_policy,
                                  prevent_cse=False)

        def scan_fn(carry, layer_params):
            x, key, li = carry
            key, sub = jax.random.split(key)
            depth_frac = (offset + (li + 1).astype(jnp.float32)) / L
            keep_p = 1.0 - depth_frac * (1.0 - theta)
            keep = jax.random.bernoulli(sub, keep_p)
            x_new, aux = lax.cond(
                keep,
                lambda x: body(x, layer_params, positions, attn_mask),
                lambda x: (x, jnp.float32(0.0)),
                x)
            return (x_new, key, li + 1), aux

        (x, _, _), auxs = lax.scan(scan_fn, (x, key, jnp.int32(0)), layers)
        return x, jnp.sum(auxs)


def convert_to_progressive_layer_drop(model, *, theta: float = 0.5,
                                      gamma: float = 0.001, seed: int = 23):
    """Wrap a built model with PLD (same params/specs pytree)."""
    cls = type(model)
    new_cls = type(f"PLD{cls.__name__}", (PLDMixin, cls), {})
    new = object.__new__(new_cls)
    new.__dict__.update(model.__dict__)
    new.pld_theta_min = theta
    new.pld_gamma = gamma
    new.pld_seed = seed
    new.pld_step = None
    return new
