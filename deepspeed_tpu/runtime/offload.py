"""ZeRO-Offload / ZeRO-Infinity: host-resident optimizer state + native step.

Reference: ``runtime/zero/stage_1_and_2.py:1096-1191`` (CPU offload of
grads/optimizer states + DeepSpeedCPUAdam) and the ZeRO-Infinity swap stack
(``runtime/swap_tensor/partitioned_optimizer_swapper.py``,
``pipelined_optimizer_swapper.py``, ``csrc/aio/``).

TPU-native shape of the same capability:
- fp32 master params + moments live in **host DRAM** as numpy arrays; the
  device holds only the bf16 compute copy (and transient grads).
- the update runs through the **C++ host optimizer**
  (``csrc/cpu_optimizer.cpp``, OpenMP + autovectorized AVX) with the bf16
  compute copy written in the same pass.
- ``device: nvme`` additionally pages the moment arrays to disk through the
  **C++ aio thread pool** (``csrc/aio.cpp``) with double-buffered
  prefetch: leaf i+1's moments stream in while leaf i updates — the
  pipelined swapper of ``pipelined_optimizer_swapper.py``.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from ..ops import aio as aio_mod
from ..ops import cpu_optimizer as host_opt
from ..utils.logging import log_dist


class HostOffloadOptimizer:
    """Flat per-leaf host state + native in-place updates."""

    def __init__(self, host_master: Any, optimizer, offload_cfg,
                 compute_dtype=jnp.bfloat16, fp32_names: tuple = (),
                 compute_shardings: Any = None):
        self.opt_name = optimizer.name
        self.hp = dict(optimizer.hyperparams)
        if self.opt_name not in ("adam", "adamw", "lion", "adagrad"):
            raise ValueError(
                f"offload_optimizer supports adam/adamw/lion/adagrad, "
                f"got '{self.opt_name}'")
        self.compute_dtype = compute_dtype
        self.nvme = offload_cfg.device == "nvme"
        self.count = 0

        leaves, self.treedef = jax.tree_util.tree_flatten_with_path(host_master)
        self.paths = [p for p, _ in leaves]
        self.names = [jax.tree_util.keystr(p) for p in self.paths]
        # np.array(copy=True): device_get views are read-only; master must
        # be writable and contiguous for the in-place native step
        self.master = [np.array(x, np.float32, copy=True, order="C")
                       for _, x in leaves]
        self.shapes = [m.shape for m in self.master]
        self.fp32_keep = [any(p[-1].key == n if hasattr(p[-1], "key") else False
                              for n in fp32_names) for p in self.paths]
        self.shardings = (jax.tree_util.tree_leaves(compute_shardings)
                          if compute_shardings is not None
                          else [None] * len(self.master))
        # bf16 copy-back buffers (uint16 storage, viewed as bfloat16)
        self.bf16 = [None if keep else np.zeros(m.size, np.uint16)
                     for keep, m in zip(self.fp32_keep, self.master)]
        self.two_moments = self.opt_name in ("adam", "adamw")

        if not self.nvme:
            self.m = [np.zeros(x.size, np.float32) for x in self.master]
            self.v = ([np.zeros(x.size, np.float32) for x in self.master]
                      if self.two_moments else [None] * len(self.master))
            self.aio = None
        else:
            path = offload_cfg.nvme_path or "/tmp/dstpu_nvme"
            # the shared NVMe seam (ops/aio.py): the same directory-of-
            # swap-files discipline the serving KV disk tier runs
            # through — name-based submits, fd-cache hygiene, counted
            # transport errors — instead of a private aio/path copy
            self.aio = aio_mod.AIOFileStore(
                path, n_threads=max(2, int(offload_cfg.buffer_count)),
                use_direct=False)
            self.nvme_dir = self.aio.dir
            # two swap slots of max-leaf size (double buffering)
            max_n = max(x.size for x in self.master)
            n_slots = 2
            self._slot_m = [np.zeros(max_n, np.float32) for _ in range(n_slots)]
            self._slot_v = [np.zeros(max_n, np.float32) for _ in range(n_slots)]
            self._slot_p = [np.zeros(max_n, np.float32) for _ in range(n_slots)]
            self._slot_write_tickets = [0] * n_slots
            # initialize moment files to zero; page the fp32 master to disk
            # too (the reference swaps master fp32 in the Infinity path,
            # swap_tensor/optimizer_utils.py) — host DRAM keeps only the
            # bf16 staging buffers + the fp32-kept (small) leaves.
            zero_max = np.zeros(max_n, np.float32)
            for i, x in enumerate(self.master):
                self.aio.sync_write(self._mfile(i), zero_max[:x.size])
                if self.two_moments:
                    self.aio.sync_write(self._vfile(i), zero_max[:x.size])
                if not self.fp32_keep[i]:
                    self.aio.sync_write(self._pfile(i), x.reshape(-1))
                    host_opt._f32_to_bf16_np(x.reshape(-1), self.bf16[i])
                    self.master[i] = None  # paged out
            log_dist(f"nvme offload: {len(self.master)} master+moment "
                     f"tensors in {path}", ranks=[0])

    # ------------------------------------------------------------------ files
    # bare names: the AIOFileStore owns the directory and the paths
    def _mfile(self, i):
        return f"moment1_{i}.bin"

    def _vfile(self, i):
        return f"moment2_{i}.bin"

    def _pfile(self, i):
        return f"master_{i}.bin"

    def _paged_master(self, i) -> bool:
        return self.nvme and self.master[i] is None

    # ------------------------------------------------------------- leaf step
    def _apply_leaf(self, i, p, m, v, g, lr):
        kw = dict(p_bf16=self.bf16[i])
        if self.opt_name in ("adam", "adamw"):
            host_opt.adam_step(p, m, v, g, self.count, lr,
                               betas=self.hp.get("betas", (0.9, 0.999)),
                               eps=self.hp.get("eps", 1e-8),
                               weight_decay=self.hp.get("weight_decay", 0.0),
                               adamw=self.opt_name == "adamw", **kw)
        elif self.opt_name == "lion":
            host_opt.lion_step(p, m, g, lr,
                               betas=self.hp.get("betas", (0.9, 0.99)),
                               weight_decay=self.hp.get("weight_decay", 0.0),
                               **kw)
        else:
            host_opt.adagrad_step(p, m, g, lr,
                                  eps=self.hp.get("eps", 1e-10),
                                  weight_decay=self.hp.get("weight_decay", 0.0),
                                  **kw)

    # ----------------------------------------------------------------- step
    def step(self, grads_tree, lr: float):
        """Host update over all leaves; returns the new device compute tree.
        Grads arrive clipped (the engine clips on-device in the grad step);
        with pinned-host grad outputs the D2H already happened inside the
        compiled step, overlapped with backward. ``SparseGradRows`` leaves
        (engine ``sparse_gradients``) ship only the touched embedding rows
        and are decompressed into the dense buffer the native step reads."""
        from .sparse_grads import SparseGradRows, SparseRows, add_into

        self.count += 1
        is_sparse = lambda x: isinstance(x, SparseGradRows)
        g_arrays = jax.tree_util.tree_leaves(grads_tree, is_leaf=is_sparse)
        # start all device→host DMAs before the first blocking device_get
        # (no-op for grads already in pinned host memory)
        for g in g_arrays:
            for part in (g if is_sparse(g) else (g,)):
                try:
                    part.copy_to_host_async()
                except Exception:
                    pass

        def to_dense(i, g):
            if not is_sparse(g):
                return np.ascontiguousarray(
                    np.asarray(jax.device_get(g), np.float32).reshape(-1))
            idx = np.asarray(jax.device_get(g.indices), np.int32)
            val = np.asarray(jax.device_get(g.values), np.float32)
            dense = np.zeros(self.shapes[i], np.float32)
            add_into(dense, SparseRows(indices=idx, values=val,
                                       shape=self.shapes[i]))
            return np.ascontiguousarray(dense.reshape(-1))

        g_leaves = [to_dense(i, g) for i, g in enumerate(g_arrays)]
        n = len(self.shapes)
        new_device = []

        if not self.nvme:
            for i in range(n):
                p = self.master[i].reshape(-1)
                self._apply_leaf(i, p, self.m[i], self.v[i], g_leaves[i], lr)
                new_device.append(self._to_device(i))
            return self.treedef.unflatten(new_device)

        # NVMe: double-buffered pipeline — prefetch i+1's master+moments
        # while updating i (pipelined_optimizer_swapper.py semantics).
        read_tickets = [None] * n
        read_tickets[0] = self._prefetch(0, slot=0)
        for i in range(n):
            slot = i % 2
            self.aio.wait(read_tickets[i])     # master+moments for i ready
            if i + 1 < n:
                nxt_slot = (i + 1) % 2
                # the next slot must have finished writing back leaf i-1
                if self._slot_write_tickets[nxt_slot]:
                    self.aio.wait(self._slot_write_tickets[nxt_slot])
                read_tickets[i + 1] = self._prefetch(i + 1, slot=nxt_slot)
            sz = int(np.prod(self.shapes[i]))
            m = self._slot_m[slot][:sz]
            v = self._slot_v[slot][:sz] if self.two_moments else None
            p = (self._slot_p[slot][:sz] if self._paged_master(i)
                 else self.master[i].reshape(-1))
            self._apply_leaf(i, p, m, v, g_leaves[i], lr)
            t = self.aio.submit_write(self._mfile(i), m)
            if self.two_moments:
                t = self.aio.submit_write(self._vfile(i), v)
            if self._paged_master(i):
                t = self.aio.submit_write(self._pfile(i), p)
            self._slot_write_tickets[slot] = t
            new_device.append(self._to_device(i))
        for t in self._slot_write_tickets:
            if t:
                self.aio.wait(t)
        return self.treedef.unflatten(new_device)

    def _prefetch(self, i, slot):
        sz = int(np.prod(self.shapes[i]))
        t = self.aio.submit_read(self._mfile(i), self._slot_m[slot][:sz])
        if self.two_moments:
            t = self.aio.submit_read(self._vfile(i), self._slot_v[slot][:sz])
        if self._paged_master(i):
            t = self.aio.submit_read(self._pfile(i), self._slot_p[slot][:sz])
        return t

    def _to_device(self, i):
        if self.fp32_keep[i]:
            arr = self.master[i]
        else:
            arr = self.bf16[i].view(ml_dtypes.bfloat16).reshape(self.shapes[i])
        s = self.shardings[i]
        return jax.device_put(arr, s) if s is not None else jnp.asarray(arr)

    # ------------------------------------------------------------ state views
    def device_compute_params(self):
        """Initial device compute copy from the host master."""
        out = []
        for i in range(len(self.shapes)):
            if not self.fp32_keep[i] and not self._paged_master(i):
                host_opt._f32_to_bf16_np(self.master[i].reshape(-1), self.bf16[i])
            # paged leaves: bf16 staging was refreshed at page-out time
            out.append(self._to_device(i))
        return self.treedef.unflatten(out)

    def master_tree(self):
        leaves = []
        for i, shape in enumerate(self.shapes):
            if self._paged_master(i):
                buf = np.zeros(int(np.prod(shape)), np.float32)
                self.aio.sync_read(self._pfile(i), buf)
                leaves.append(buf.reshape(shape))
            else:
                leaves.append(self.master[i].copy())
        return self.treedef.unflatten(leaves)

    def moment_trees(self):
        """(m, v) host trees — NVMe moments are paged in for this call
        (checkpointing path)."""
        if not self.nvme:
            m = self.treedef.unflatten([x.reshape(s) for x, s in
                                        zip(self.m, self.shapes)])
            v = (self.treedef.unflatten([x.reshape(s) for x, s in
                                         zip(self.v, self.shapes)])
                 if self.two_moments else None)
            return m, v
        ms, vs = [], []
        for i, shape in enumerate(self.shapes):
            sz = int(np.prod(shape))
            buf = np.zeros(sz, np.float32)
            self.aio.sync_read(self._mfile(i), buf)
            ms.append(buf.reshape(shape))
            if self.two_moments:
                buf2 = np.zeros(sz, np.float32)
                self.aio.sync_read(self._vfile(i), buf2)
                vs.append(buf2.reshape(shape))
        return (self.treedef.unflatten(ms),
                self.treedef.unflatten(vs) if self.two_moments else None)

    def load_state(self, master_tree, m_tree=None, v_tree=None, count=0):
        """Restore host state (checkpoint resume)."""
        self.count = int(count)
        for i, (_, x) in enumerate(
                jax.tree_util.tree_flatten_with_path(master_tree)[0]):
            xf = np.ascontiguousarray(np.asarray(x, np.float32))
            if self._paged_master(i):
                self.aio.sync_write(self._pfile(i), xf.reshape(-1))
                host_opt._f32_to_bf16_np(xf.reshape(-1), self.bf16[i])
            else:
                np.copyto(self.master[i], xf)
        if m_tree is not None:
            m_leaves = jax.tree_util.tree_leaves(m_tree)
            v_leaves = (jax.tree_util.tree_leaves(v_tree)
                        if v_tree is not None else [None] * len(m_leaves))
            for i in range(len(self.master)):
                mi = np.ascontiguousarray(
                    np.asarray(m_leaves[i], np.float32).reshape(-1))
                if not self.nvme:
                    np.copyto(self.m[i], mi)
                    if self.two_moments and v_leaves[i] is not None:
                        np.copyto(self.v[i], np.asarray(
                            v_leaves[i], np.float32).reshape(-1))
                else:
                    self.aio.sync_write(self._mfile(i), mi)
                    if self.two_moments and v_leaves[i] is not None:
                        self.aio.sync_write(self._vfile(i), np.ascontiguousarray(
                            np.asarray(v_leaves[i], np.float32).reshape(-1)))
