"""Standalone checkpoint → fp32 converter (``dstpu_to_fp32`` CLI).

Analog of the reference's ``utils/zero_to_fp32.py`` (587 LoC, shipped inside
every checkpoint dir) which stitches per-rank ZeRO shard files back into one
fp32 state dict. Here the store is already one logical sharded checkpoint,
so "conversion" is a plain restore — no engine, no mesh, no live model — and
the output is either raw fp32 ``.safetensors`` (native param tree) or a full
HF checkpoint when the architecture maps to an exporter family.

    dstpu_to_fp32 /ckpts/run latest out/fp32 --format hf

Reads ``meta.json``'s ``model_config`` (written at save time) to rebuild the
:class:`TransformerConfig`; both on-disk layouts keep the master under the
same top-level key, so one restore path serves host and device checkpoints.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path


def _load_master(ckpt_path: Path):
    """(master_params fp32 numpy tree, meta dict) from a tag directory.

    Both on-disk layouts (host numpy trees / device TrainState) keep the
    master under the top-level ``master_params`` key; only that subtree is
    restored — moments are master-sized, so a full restore would read ~3x
    the necessary bytes."""
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    meta_file = ckpt_path / "meta.json"
    meta = json.loads(meta_file.read_text()) if meta_file.exists() else {}
    ckptr = ocp.PyTreeCheckpointer()
    try:
        skeleton = ckptr.metadata(ckpt_path / "state")
        item = {"master_params": skeleton["master_params"]}
        restored = ckptr.restore(ckpt_path / "state", item=item)
        master = restored["master_params"]
    except Exception:
        restored = ckptr.restore(ckpt_path / "state")
        master = restored["master_params"]
    del restored
    return jax.tree.map(lambda a: np.asarray(a, np.float32), master), meta


def model_config_from_meta(meta: dict):
    """Rebuild the TransformerConfig stored by ``save_checkpoint`` (None if
    the checkpointed model had no dataclass config)."""
    mc = meta.get("model_config")
    if not mc:
        return None
    import jax.numpy as jnp

    from ...models.transformer import TransformerConfig

    mc = dict(mc)
    dtype = mc.get("dtype")
    if isinstance(dtype, str):
        mc["dtype"] = getattr(jnp, dtype, jnp.bfloat16)
    return TransformerConfig(**mc)


def convert(ckpt_dir: str, tag: str | None = None, out_dir: str = "fp32_out",
            fmt: str = "auto") -> str:
    """Restore the fp32 master tree and write it out.

    ``fmt``: "hf" (config.json + model.safetensors via the exporter),
    "safetensors" (flat native tree), or "auto" (hf when the architecture
    maps to an exporter family, else safetensors).
    """
    base = Path(ckpt_dir).absolute()
    if tag in (None, "latest"):
        latest = base / "latest"
        if not latest.exists():
            raise FileNotFoundError(f"no 'latest' tag file in {base}")
        tag = latest.read_text().strip()
    master, meta = _load_master(base / tag)
    cfg = model_config_from_meta(meta)
    if fmt == "hf" and cfg is None:
        raise ValueError(
            "--format hf requires a checkpoint whose meta.json carries "
            "model_config (written by save_checkpoint for TransformerConfig "
            "models); this checkpoint has none — use --format safetensors")
    os.makedirs(out_dir, exist_ok=True)

    if fmt in ("hf", "auto") and cfg is not None:
        try:
            from ...models.exporter import export_hf_checkpoint

            export_hf_checkpoint(master, cfg, out_dir)
            return out_dir
        except Exception:
            if fmt == "hf":
                raise
            # auto: clear any half-written HF files before the fallback so
            # the out_dir never looks like a broken HF checkpoint
            for name in ("config.json", "model.safetensors"):
                try:
                    os.unlink(os.path.join(out_dir, name))
                except OSError:
                    pass

    # native flat safetensors: /-joined tree paths -> fp32 tensors
    import jax
    from safetensors.numpy import save_file

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(master)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        flat[key] = leaf
    save_file(flat, os.path.join(out_dir, "model_fp32.safetensors"))
    if cfg is not None:
        (Path(out_dir) / "native_config.json").write_text(
            json.dumps(meta.get("model_config"), indent=2))
    return out_dir


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="dstpu_to_fp32",
        description="checkpoint -> consolidated fp32 weights "
                    "(reference utils/zero_to_fp32.py analog)")
    p.add_argument("ckpt_dir", help="directory holding tags + 'latest'")
    p.add_argument("tag", nargs="?", default=None)
    p.add_argument("out_dir", nargs="?", default="fp32_out")
    p.add_argument("--format", choices=("auto", "hf", "safetensors"),
                   default="auto")
    args = p.parse_args(argv)
    out = convert(args.ckpt_dir, args.tag, args.out_dir, args.format)
    print(f"wrote consolidated fp32 weights to {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
