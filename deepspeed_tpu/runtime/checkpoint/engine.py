"""Checkpoint save/load.

TPU-native analog of the reference checkpoint stack (``engine.py:2653,2982``
+ pluggable ``runtime/checkpoint_engine/``): one *logical* checkpoint in a
sharded array store (orbax/tensorstore), written collectively by all hosts —
universal-by-construction. Where the reference writes per-(dp,tp,pp)-rank
shard files and needs an offline converter (``checkpoint/ds_to_universal.py``)
to reshape between topologies, here restore-onto-any-mesh is native: load
targets are specified as abstract (shape, sharding) and tensorstore reshards.

Layout per tag directory:
    <dir>/<tag>/state/...      sharded TrainState (master params, moments, step)
    <dir>/<tag>/meta.json      config + model metadata
    <dir>/latest               tag pointer (same contract as the reference)
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from ...utils.logging import log_dist


def _checkpointer():
    return ocp.PyTreeCheckpointer()


def save_checkpoint(engine, save_dir: str, tag: str | None = None) -> str:
    tag = tag or f"global_step{engine.global_steps}"
    base = Path(save_dir).absolute()
    path = base / tag
    ckptr = _checkpointer()
    ckptr.save(path / "state", engine.state, force=True)
    if jax.process_index() == 0:
        meta = {
            "tag": tag,
            "global_steps": engine.global_steps,
            "config": engine.config.to_dict(),
            "param_count": engine.param_count,
            "mesh": dict(engine.mesh.shape),
        }
        (path / "meta.json").write_text(json.dumps(meta, indent=2))
        (base / "latest").write_text(tag)
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return str(path)


def load_checkpoint(engine, load_dir: str, tag: str | None = None) -> str:
    base = Path(load_dir).absolute()
    if tag is None:
        latest = base / "latest"
        if not latest.exists():
            raise FileNotFoundError(f"no 'latest' tag file in {base}")
        tag = latest.read_text().strip()
    path = base / tag
    ckptr = _checkpointer()
    # Abstract target carries this engine's shardings: restoring onto a
    # different mesh/topology reshards transparently (elastic resume).
    abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        engine.state, engine.state_shardings)
    restored = ckptr.restore(path / "state", item=abstract)
    engine.state = restored
    meta_file = path / "meta.json"
    if meta_file.exists():
        meta = json.loads(meta_file.read_text())
        engine.global_steps = int(meta.get("global_steps", int(restored.step)))
    else:
        engine.global_steps = int(restored.step)
    log_dist(f"loaded checkpoint {path} (step {engine.global_steps})", ranks=[0])
    return str(path)
