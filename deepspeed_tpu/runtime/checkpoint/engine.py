"""Checkpoint save/load.

TPU-native analog of the reference checkpoint stack (``engine.py:2653,2982``
+ pluggable ``runtime/checkpoint_engine/``): one *logical* checkpoint in a
sharded array store (orbax/tensorstore), written collectively by all hosts —
universal-by-construction. Where the reference writes per-(dp,tp,pp)-rank
shard files and needs an offline converter (``checkpoint/ds_to_universal.py``)
to reshape between topologies, here restore-onto-any-mesh is native: load
targets are specified as abstract (shape, sharding) and tensorstore reshards.

Layout per tag directory:
    <dir>/<tag>/state/...      sharded TrainState (master params, moments, step)
    <dir>/<tag>/meta.json      config + model metadata
    <dir>/<tag>/manifest.json  integrity manifest — the commit marker,
                               written LAST (resilience/integrity.py)
    <dir>/latest               tag pointer (same contract as the reference)

Commit protocol (crash-safe by ordering, chaos-tested): state → meta →
manifest → ``latest``. A death anywhere in between leaves ``latest`` at
the previous durable checkpoint, and load-time verification falls back
to the newest VERIFIED tag if the pointed-at one is torn.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from ...resilience import chaos
from ...resilience.guards import CheckpointIntegrityError
from ...resilience.integrity import (list_tags, newest_verified_tag,
                                     prune_tags, verify_tag, write_manifest)
from ...utils.logging import log_dist, warning_once


def _checkpointer(engine=None):
    """Sync or async checkpointer per ``config.checkpoint.async_save``
    (reference pluggable CheckpointEngine / Nebula async service): the async
    path initiates the tensorstore writes and returns — training resumes
    while the commit happens in background threads. One AsyncCheckpointer is
    cached per engine so in-flight saves can be awaited."""
    async_save = (engine is not None
                  and getattr(engine.config.checkpoint, "async_save", False))
    if not async_save:
        return ocp.PyTreeCheckpointer(), False
    ck = getattr(engine, "_async_ckptr", None)
    if ck is None:
        ck = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        engine._async_ckptr = ck
    return ck, True


def _model_config_dict(model):
    """JSON-safe dump of the model's TransformerConfig (None if absent)."""
    import dataclasses

    cfg = getattr(model, "cfg", None)
    if cfg is None or not dataclasses.is_dataclass(cfg):
        return None
    out = {}
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if f.name == "dtype":
            v = getattr(v, "__name__", str(v))
        try:
            json.dumps(v)
        except (TypeError, ValueError):
            # custom-dataclass fields (callables, enums, ...) must never
            # break save_checkpoint itself; drop them from the meta dump
            continue
        out[f.name] = v
    return out


def _validate_tag(engine, tag: str) -> None:
    """Cross-process tag consistency (reference ``engine.py:2965``
    ``checkpoint_tag_validation``). Uses an allgather so EVERY rank sees the
    mismatch and fails/warns uniformly — a one-sided check would leave rank 0
    entering the collective save alone and hanging."""
    mode = engine.config.checkpoint.tag_validation
    if mode == "ignore" or jax.process_count() == 1:
        return
    import hashlib

    from jax.experimental import multihost_utils

    mine = int.from_bytes(hashlib.sha256(tag.encode()).digest()[:4], "big")
    all_hashes = np.asarray(multihost_utils.process_allgather(np.int64(mine)))
    if not np.all(all_hashes == all_hashes[0]):
        msg = (f"checkpoint tag {tag!r} differs across processes "
               "(hash mismatch) — ranks would write inconsistent checkpoints")
        if mode == "fail":
            raise ValueError(msg)
        log_dist(f"WARNING: {msg}")


def _commit_tag(engine, base: Path, tag: str) -> None:
    """The durable-commit epilogue, shared by the sync and async paths:
    write the manifest (the commit marker — LAST artifact inside the
    tag), flip ``latest``, prune old tags. Rank 0 only; the chaos kill
    points bracket exactly the window the crash-mid-commit test targets."""
    chaos.kill_point(chaos.KILL_AFTER_STATE_WRITE)
    if jax.process_index() == 0:
        level = getattr(engine.config.checkpoint, "verify", "size")
        write_manifest(base / tag, level,
                       extra={"global_steps": engine.global_steps})
        chaos.kill_point(chaos.KILL_BEFORE_LATEST_FLIP)
        (base / "latest").write_text(tag)
        keep = int(getattr(engine.config.checkpoint, "keep_last", 0) or 0)
        if keep:
            prune_tags(base, keep, protect={tag})


def wait_for_checkpoint(engine) -> None:
    """Block until any in-flight async save has committed, then write the
    manifest and flip the 'latest' pointer — so a crash mid-commit leaves
    'latest' at the previous DURABLE checkpoint, never at a half-written
    one, and every tag 'latest' ever names carries a commit marker."""
    ck = getattr(engine, "_async_ckptr", None)
    if ck is not None:
        ck.wait_until_finished()
    pending = getattr(engine, "_pending_latest", None)
    if pending is not None:
        base, tag = pending
        _commit_tag(engine, Path(base), tag)
        engine._pending_latest = None


def save_checkpoint(engine, save_dir: str, tag: str | None = None) -> str:
    tag = tag or f"global_step{engine.global_steps}"
    _validate_tag(engine, tag)
    base = Path(save_dir).absolute()
    path = base / tag
    ckptr, is_async = _checkpointer(engine)
    if is_async:
        wait_for_checkpoint(engine)   # one in-flight save at a time
    if getattr(engine, "offload", False):
        # host-resident state (ZeRO-Offload/Infinity): numpy trees
        m, v = engine.host_opt.moment_trees()
        state = {"master_params": engine.host_opt.master_tree(),
                 "mu": m, "count": np.int32(engine.host_opt.count)}
        if v is not None:
            state["nu"] = v
        ckptr.save(path / "state", state, force=True)
    else:
        ckptr.save(path / "state", engine.state, force=True)
    if jax.process_index() == 0:
        meta = {
            "tag": tag,
            "global_steps": engine.global_steps,
            "config": engine.config.to_dict(),
            "param_count": engine.param_count,
            "mesh": dict(engine.mesh.shape),
            # model architecture, when the model exposes a TransformerConfig:
            # lets the standalone dstpu_to_fp32 converter rebuild the HF
            # export without the engine (reference utils/zero_to_fp32.py,
            # which ships INSIDE every checkpoint for the same reason)
            "model_config": _model_config_dict(engine.model),
            # state layout on disk: "host" = offload engine's numpy trees,
            # "device" = TrainState. load_checkpoint converts across layouts
            # so offload <-> device restores work in both directions.
            "layout": "host" if getattr(engine, "offload", False) else "device",
        }
        ls = getattr(engine, "_offload_ls", None)
        if getattr(engine, "offload", False) and ls is not None:
            # host-side fp16 loss-scale state (bf16/fp32 runs carry the
            # inert scale=1 record — harmless, kept for layout uniformity)
            meta["offload_loss_scale"] = {
                "scale": float(ls.scale), "good_steps": int(ls.good_steps),
                "hysteresis": int(ls.hysteresis)}
        moq = getattr(engine, "_moq", None)
        if moq is not None:
            # the MoQ schedule lives outside the jitted state (bit width is
            # a static argument): resume must not restart QAT at start_bits
            meta["moq"] = {"bits": moq.bits, "initial_eig": moq.initial_eig,
                           "history": moq.history}
        (path / "meta.json").write_text(json.dumps(meta, indent=2))
    if is_async:
        # manifest + 'latest' flip only after the background commit is
        # durable (wait_for_checkpoint → _commit_tag)
        engine._pending_latest = (str(base), tag)
    else:
        _commit_tag(engine, base, tag)
    log_dist(f"saved checkpoint {path}"
             + (" (async, committing in background)" if is_async else ""),
             ranks=[0])
    return str(path)


def _resolve_verified_tag(engine, base: Path, tag: str | None) -> str:
    """Pick the tag to restore: ``latest`` (or the explicit ``tag``),
    verified against its manifest; on corruption fall back to the newest
    tag that DOES verify. Explicit tags never fall back silently —
    restoring a different checkpoint than the one the caller pinned would
    be worse than failing."""
    level = getattr(engine.config.checkpoint, "verify", "size")
    explicit = tag is not None
    if tag is None:
        latest = base / "latest"
        if latest.exists():
            tag = latest.read_text().strip()
        else:
            # no pointer (crash before the first flip, or manual surgery):
            # the newest verified tag is the best truth available
            tag = newest_verified_tag(base, level)
            if tag is None:
                raise FileNotFoundError(
                    f"no 'latest' tag file and no loadable tag in {base}")
            log_dist(f"load_checkpoint: no 'latest' pointer in {base}; "
                     f"using newest verified tag {tag!r}", ranks=[0],
                     level="WARNING")
    status, reason = verify_tag(base / tag, level)
    if status == "legacy":
        warning_once(f"checkpoint {tag!r} has no integrity manifest "
                     "(pre-resilience save?) — loading unverified; re-save "
                     "to get crash-safe commits")
    elif status == "corrupt":
        if explicit:
            raise CheckpointIntegrityError(
                f"checkpoint tag {tag!r} failed verification ({reason}); "
                "refusing to restore a pinned tag from torn bytes",
                tag=tag, reason=reason)
        fb = newest_verified_tag(base, level, exclude={tag})
        if fb is None:
            raise CheckpointIntegrityError(
                f"checkpoint {tag!r} failed verification ({reason}) and no "
                f"older verified tag exists in {base}", tag=tag,
                reason=reason)
        log_dist(f"load_checkpoint: tag {tag!r} failed verification "
                 f"({reason}) — falling back to newest verified tag {fb!r}",
                 ranks=[0], level="WARNING")
        tag = fb
    return tag


def load_checkpoint(engine, load_dir: str, tag: str | None = None) -> str:
    wait_for_checkpoint(engine)   # an in-flight save must commit first
    base = Path(load_dir).absolute()
    tag = _resolve_verified_tag(engine, base, tag)
    _validate_tag(engine, tag)
    if engine.config.checkpoint.load_universal:
        # universal-by-construction: every checkpoint already restores onto
        # any topology (abstract-target reshard); the flag is satisfied
        log_dist("load_universal: checkpoints reshard natively; no offline "
                 "conversion needed", ranks=[0])
    path = base / tag
    ckptr = ocp.PyTreeCheckpointer()
    meta_file = path / "meta.json"
    meta_pre = json.loads(meta_file.read_text()) if meta_file.exists() else {}
    layout = meta_pre.get("layout")
    to_host = getattr(engine, "offload", False)
    raw = None
    if layout is None:
        # pre-"layout" checkpoints: the store is OCDBT (no per-leaf dirs on
        # disk), so sniff the tree structure — the host layout alone has a
        # top-level optimizer step "count". Metadata reads no array data;
        # fall back to a full (unsharded) restore only if it's unavailable.
        try:
            keys = set(ckptr.metadata(path / "state").keys())
        except Exception:
            raw = ckptr.restore(path / "state")
            keys = set(raw)
        layout = "host" if "count" in keys else "device"

    def _host_trees():
        """(master, mu, nu, count) from either on-disk layout. The count is
        the *applied-update* count (fp16 overflow skips excluded) — Adam
        bias correction depends on it, so it must never be seeded from the
        every-batch ``step`` counter."""
        r = raw if raw is not None else ckptr.restore(path / "state")
        src = r if layout == "host" else r["opt_state"]
        return (r["master_params"], src.get("mu"), src.get("nu"),
                int(np.asarray(src["count"])))

    if to_host:
        # restore into the host optimizer (offload engine), whichever engine
        # kind wrote the checkpoint
        master, mu, nu, count = _host_trees()
        engine.host_opt.load_state(master, mu, nu, count=count)
        with engine.mesh:
            engine.compute_params = engine.host_opt.device_compute_params()
        ls_meta = meta_pre.get("offload_loss_scale")
        if ls_meta is not None and engine.config.fp16.enabled:
            import jax.numpy as jnp

            from ..loss_scaler import LossScaleState
            engine._offload_ls = LossScaleState(
                scale=jnp.float32(ls_meta["scale"]),
                good_steps=jnp.int32(ls_meta["good_steps"]),
                hysteresis=jnp.int32(ls_meta["hysteresis"]))
        step_guess = count
    elif layout == "host":
        # host optimizer trees -> device TrainState: rebuild the state pytree
        # around the stored master/moments, then shard onto this engine's
        # mesh (fresh loss-scale/residual slots — the host engine has none).
        master, mu, nu, count = _host_trees()
        state = engine.state
        opt_state = state.opt_state._replace(
            mu=jax.tree.map(lambda cur, new: np.asarray(new, cur.dtype),
                            state.opt_state.mu, mu),
            nu=(jax.tree.map(lambda cur, new: np.asarray(new, cur.dtype),
                             state.opt_state.nu, nu)
                if nu is not None else state.opt_state.nu),
            count=np.asarray(count, dtype=np.int32),
        )
        new_state = state._replace(
            step=np.asarray(count, dtype=np.int32),
            master_params=jax.tree.map(
                lambda cur, new: np.asarray(new, cur.dtype),
                state.master_params, master),
            opt_state=opt_state,
        )
        engine.state = jax.tree.map(
            lambda x, s: jax.device_put(x, s), new_state, engine.state_shardings)
        step_guess = count
    else:
        # Abstract target + explicit per-leaf restore_args carry this
        # engine's shardings: restoring onto a different mesh/topology
        # reshards transparently (elastic resume). restore_args is required —
        # without it orbax re-applies the *saved* topology's shardings from
        # the sharding file, and the train step then rejects the arrays.
        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            engine.state, engine.state_shardings)
        restore_args = jax.tree.map(
            lambda x, s: ocp.ArrayRestoreArgs(sharding=s, dtype=x.dtype),
            engine.state, engine.state_shardings)
        # The engine may want error-feedback residuals the checkpoint
        # can't supply: a pre-error-feedback int8 save (comm_err == {}),
        # an fp-mode save resumed under int8/onebit, or an elastic/
        # bucket-plan change that resized the flat residual vectors.
        # Probe the checkpoint's ACTUAL saved structure up front and
        # zero-init only on a genuine mismatch — catching restore
        # failures instead would zero valid residuals on a transient
        # error and mask unrelated corruption with the retry's traceback.
        want_err = getattr(engine.state, "comm_err", None) or None
        mismatch = False
        if want_err:
            want_shapes = {k: tuple(v.shape) for k, v in want_err.items()}
            try:
                saved = ckptr.metadata(path / "state").get("comm_err") or {}
                saved_shapes = {k: tuple(m.shape) for k, m in saved.items()}
                mismatch = saved_shapes != want_shapes
            except Exception as e:
                log_dist("load_checkpoint: could not probe the saved "
                         f"comm_err structure ({e}) — restoring strictly",
                         ranks=[0])
        if mismatch:
            restored = ckptr.restore(
                path / "state", item=abstract._replace(comm_err={}),
                restore_args=restore_args._replace(comm_err={}))
            restored = restored._replace(comm_err=engine.state.comm_err)
            log_dist("load_checkpoint: checkpoint comm_err residuals "
                     f"{saved_shapes or 'absent'} don't match this run's "
                     f"{want_shapes} (pre-error-feedback save, changed "
                     "bucket plan, or changed data world) — zero-"
                     "initialized; error feedback re-debiases from the "
                     "next step", ranks=[0])
        else:
            restored = ckptr.restore(path / "state", item=abstract,
                                     restore_args=restore_args)
        engine.state = restored
        step_guess = int(restored.step)
    engine.global_steps = int(meta_pre.get("global_steps", step_guess))
    moq_meta = meta_pre.get("moq")
    if getattr(engine, "_moq", None) is not None:
        if moq_meta:
            engine._moq.bits = int(moq_meta["bits"])
            engine._moq.initial_eig = moq_meta.get("initial_eig")
            engine._moq.history = [tuple(h)
                                   for h in moq_meta.get("history", [])]
        else:
            # no schedule in the checkpoint (pre-MoQ save): RESET to the
            # fresh state — keeping an already-narrowed in-process schedule
            # would silently diverge from a fresh-process resume of the
            # same checkpoint
            moq = engine._moq
            cfg_wq = engine.config.compression.weight_quantization
            moq.bits = int(cfg_wq.start_bits or cfg_wq.bits)
            moq.initial_eig = None
            moq.history = []
            log_dist("load_checkpoint: MoQ enabled but the checkpoint "
                     "carries no schedule (pre-MoQ save?) — QAT restarts "
                     f"at start_bits={moq.bits}", ranks=[0])
    # Re-baseline the non-finite sentinel: the restored state carries the
    # run's HISTORICAL skipped_steps total — without this, the first
    # report boundary after a resume would read all of history as one
    # fresh all-skipped window and halt a healthy run (and resume="auto"
    # would then halt every incarnation the same way).
    if hasattr(engine, "_skipped_total_prev") and not to_host:
        engine._skipped_total_prev = float(
            np.asarray(engine.state.skipped_steps))
    if hasattr(engine, "_bad_step_streak"):
        engine._bad_step_streak = 0
    log_dist(f"loaded checkpoint {path} (step {engine.global_steps})", ranks=[0])
    return str(path)


def auto_resume(engine, load_dir: str | None) -> Optional[str]:
    """``resilience.resume == "auto"``: restore the newest loadable
    checkpoint under ``load_dir`` if the directory holds any, else start
    fresh. Returns the restored path or None (fresh run). This is what
    makes a restart-loop incarnation (elasticity/agent.py) and a manual
    relaunch indistinguishable: both just construct the engine."""
    if not load_dir:
        raise ValueError(
            'resilience.resume == "auto" requires resilience.resume_dir '
            "(the directory save_checkpoint writes to)")
    base = Path(load_dir).absolute()
    if not base.is_dir() or not list_tags(base):
        log_dist(f"auto-resume: no checkpoints in {base} — fresh run",
                 ranks=[0])
        return None
    try:
        return load_checkpoint(engine, str(base))
    except FileNotFoundError as e:
        # tag dirs exist but none is committed (e.g. the FIRST save of the
        # run died mid-state-write): that's a fresh run, not an error —
        # there was never a durable checkpoint to lose
        log_dist(f"auto-resume: no committed checkpoint in {base} ({e}) — "
                 "fresh run", ranks=[0], level="WARNING")
        return None
