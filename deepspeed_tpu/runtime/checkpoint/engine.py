"""Checkpoint save/load.

TPU-native analog of the reference checkpoint stack (``engine.py:2653,2982``
+ pluggable ``runtime/checkpoint_engine/``): one *logical* checkpoint in a
sharded array store (orbax/tensorstore), written collectively by all hosts —
universal-by-construction. Where the reference writes per-(dp,tp,pp)-rank
shard files and needs an offline converter (``checkpoint/ds_to_universal.py``)
to reshape between topologies, here restore-onto-any-mesh is native: load
targets are specified as abstract (shape, sharding) and tensorstore reshards.

Layout per tag directory:
    <dir>/<tag>/state/...      sharded TrainState (master params, moments, step)
    <dir>/<tag>/meta.json      config + model metadata
    <dir>/latest               tag pointer (same contract as the reference)
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from ...utils.logging import log_dist


def _checkpointer(engine=None):
    """Sync or async checkpointer per ``config.checkpoint.async_save``
    (reference pluggable CheckpointEngine / Nebula async service): the async
    path initiates the tensorstore writes and returns — training resumes
    while the commit happens in background threads. One AsyncCheckpointer is
    cached per engine so in-flight saves can be awaited."""
    async_save = (engine is not None
                  and getattr(engine.config.checkpoint, "async_save", False))
    if not async_save:
        return ocp.PyTreeCheckpointer(), False
    ck = getattr(engine, "_async_ckptr", None)
    if ck is None:
        ck = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        engine._async_ckptr = ck
    return ck, True


def _model_config_dict(model):
    """JSON-safe dump of the model's TransformerConfig (None if absent)."""
    import dataclasses

    cfg = getattr(model, "cfg", None)
    if cfg is None or not dataclasses.is_dataclass(cfg):
        return None
    out = {}
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if f.name == "dtype":
            v = getattr(v, "__name__", str(v))
        try:
            json.dumps(v)
        except (TypeError, ValueError):
            # custom-dataclass fields (callables, enums, ...) must never
            # break save_checkpoint itself; drop them from the meta dump
            continue
        out[f.name] = v
    return out


def _validate_tag(engine, tag: str) -> None:
    """Cross-process tag consistency (reference ``engine.py:2965``
    ``checkpoint_tag_validation``). Uses an allgather so EVERY rank sees the
    mismatch and fails/warns uniformly — a one-sided check would leave rank 0
    entering the collective save alone and hanging."""
    mode = engine.config.checkpoint.tag_validation
    if mode == "ignore" or jax.process_count() == 1:
        return
    import hashlib

    from jax.experimental import multihost_utils

    mine = int.from_bytes(hashlib.sha256(tag.encode()).digest()[:4], "big")
    all_hashes = np.asarray(multihost_utils.process_allgather(np.int64(mine)))
    if not np.all(all_hashes == all_hashes[0]):
        msg = (f"checkpoint tag {tag!r} differs across processes "
               "(hash mismatch) — ranks would write inconsistent checkpoints")
        if mode == "fail":
            raise ValueError(msg)
        log_dist(f"WARNING: {msg}")


def wait_for_checkpoint(engine) -> None:
    """Block until any in-flight async save has committed, then flip the
    'latest' pointer — so a crash mid-commit leaves 'latest' at the previous
    DURABLE checkpoint, never at a half-written one."""
    ck = getattr(engine, "_async_ckptr", None)
    if ck is not None:
        ck.wait_until_finished()
    pending = getattr(engine, "_pending_latest", None)
    if pending is not None:
        base, tag = pending
        if jax.process_index() == 0:
            (Path(base) / "latest").write_text(tag)
        engine._pending_latest = None


def save_checkpoint(engine, save_dir: str, tag: str | None = None) -> str:
    tag = tag or f"global_step{engine.global_steps}"
    _validate_tag(engine, tag)
    base = Path(save_dir).absolute()
    path = base / tag
    ckptr, is_async = _checkpointer(engine)
    if is_async:
        wait_for_checkpoint(engine)   # one in-flight save at a time
    if getattr(engine, "offload", False):
        # host-resident state (ZeRO-Offload/Infinity): numpy trees
        m, v = engine.host_opt.moment_trees()
        state = {"master_params": engine.host_opt.master_tree(),
                 "mu": m, "count": np.int32(engine.host_opt.count)}
        if v is not None:
            state["nu"] = v
        ckptr.save(path / "state", state, force=True)
    else:
        ckptr.save(path / "state", engine.state, force=True)
    if jax.process_index() == 0:
        meta = {
            "tag": tag,
            "global_steps": engine.global_steps,
            "config": engine.config.to_dict(),
            "param_count": engine.param_count,
            "mesh": dict(engine.mesh.shape),
            # model architecture, when the model exposes a TransformerConfig:
            # lets the standalone dstpu_to_fp32 converter rebuild the HF
            # export without the engine (reference utils/zero_to_fp32.py,
            # which ships INSIDE every checkpoint for the same reason)
            "model_config": _model_config_dict(engine.model),
            # state layout on disk: "host" = offload engine's numpy trees,
            # "device" = TrainState. load_checkpoint converts across layouts
            # so offload <-> device restores work in both directions.
            "layout": "host" if getattr(engine, "offload", False) else "device",
        }
        ls = getattr(engine, "_offload_ls", None)
        if getattr(engine, "offload", False) and ls is not None:
            # host-side fp16 loss-scale state (bf16/fp32 runs carry the
            # inert scale=1 record — harmless, kept for layout uniformity)
            meta["offload_loss_scale"] = {
                "scale": float(ls.scale), "good_steps": int(ls.good_steps),
                "hysteresis": int(ls.hysteresis)}
        moq = getattr(engine, "_moq", None)
        if moq is not None:
            # the MoQ schedule lives outside the jitted state (bit width is
            # a static argument): resume must not restart QAT at start_bits
            meta["moq"] = {"bits": moq.bits, "initial_eig": moq.initial_eig,
                           "history": moq.history}
        (path / "meta.json").write_text(json.dumps(meta, indent=2))
        if not is_async:
            (base / "latest").write_text(tag)
    if is_async:
        # 'latest' flips only after the background commit is durable
        engine._pending_latest = (str(base), tag)
    log_dist(f"saved checkpoint {path}"
             + (" (async, committing in background)" if is_async else ""),
             ranks=[0])
    return str(path)


def load_checkpoint(engine, load_dir: str, tag: str | None = None) -> str:
    wait_for_checkpoint(engine)   # an in-flight save must commit first
    base = Path(load_dir).absolute()
    if tag is None:
        latest = base / "latest"
        if not latest.exists():
            raise FileNotFoundError(f"no 'latest' tag file in {base}")
        tag = latest.read_text().strip()
    _validate_tag(engine, tag)
    if engine.config.checkpoint.load_universal:
        # universal-by-construction: every checkpoint already restores onto
        # any topology (abstract-target reshard); the flag is satisfied
        log_dist("load_universal: checkpoints reshard natively; no offline "
                 "conversion needed", ranks=[0])
    path = base / tag
    ckptr = ocp.PyTreeCheckpointer()
    meta_file = path / "meta.json"
    meta_pre = json.loads(meta_file.read_text()) if meta_file.exists() else {}
    layout = meta_pre.get("layout")
    to_host = getattr(engine, "offload", False)
    raw = None
    if layout is None:
        # pre-"layout" checkpoints: the store is OCDBT (no per-leaf dirs on
        # disk), so sniff the tree structure — the host layout alone has a
        # top-level optimizer step "count". Metadata reads no array data;
        # fall back to a full (unsharded) restore only if it's unavailable.
        try:
            keys = set(ckptr.metadata(path / "state").keys())
        except Exception:
            raw = ckptr.restore(path / "state")
            keys = set(raw)
        layout = "host" if "count" in keys else "device"

    def _host_trees():
        """(master, mu, nu, count) from either on-disk layout. The count is
        the *applied-update* count (fp16 overflow skips excluded) — Adam
        bias correction depends on it, so it must never be seeded from the
        every-batch ``step`` counter."""
        r = raw if raw is not None else ckptr.restore(path / "state")
        src = r if layout == "host" else r["opt_state"]
        return (r["master_params"], src.get("mu"), src.get("nu"),
                int(np.asarray(src["count"])))

    if to_host:
        # restore into the host optimizer (offload engine), whichever engine
        # kind wrote the checkpoint
        master, mu, nu, count = _host_trees()
        engine.host_opt.load_state(master, mu, nu, count=count)
        with engine.mesh:
            engine.compute_params = engine.host_opt.device_compute_params()
        ls_meta = meta_pre.get("offload_loss_scale")
        if ls_meta is not None and engine.config.fp16.enabled:
            import jax.numpy as jnp

            from ..loss_scaler import LossScaleState
            engine._offload_ls = LossScaleState(
                scale=jnp.float32(ls_meta["scale"]),
                good_steps=jnp.int32(ls_meta["good_steps"]),
                hysteresis=jnp.int32(ls_meta["hysteresis"]))
        step_guess = count
    elif layout == "host":
        # host optimizer trees -> device TrainState: rebuild the state pytree
        # around the stored master/moments, then shard onto this engine's
        # mesh (fresh loss-scale/residual slots — the host engine has none).
        master, mu, nu, count = _host_trees()
        state = engine.state
        opt_state = state.opt_state._replace(
            mu=jax.tree.map(lambda cur, new: np.asarray(new, cur.dtype),
                            state.opt_state.mu, mu),
            nu=(jax.tree.map(lambda cur, new: np.asarray(new, cur.dtype),
                             state.opt_state.nu, nu)
                if nu is not None else state.opt_state.nu),
            count=np.asarray(count, dtype=np.int32),
        )
        new_state = state._replace(
            step=np.asarray(count, dtype=np.int32),
            master_params=jax.tree.map(
                lambda cur, new: np.asarray(new, cur.dtype),
                state.master_params, master),
            opt_state=opt_state,
        )
        engine.state = jax.tree.map(
            lambda x, s: jax.device_put(x, s), new_state, engine.state_shardings)
        step_guess = count
    else:
        # Abstract target + explicit per-leaf restore_args carry this
        # engine's shardings: restoring onto a different mesh/topology
        # reshards transparently (elastic resume). restore_args is required —
        # without it orbax re-applies the *saved* topology's shardings from
        # the sharding file, and the train step then rejects the arrays.
        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            engine.state, engine.state_shardings)
        restore_args = jax.tree.map(
            lambda x, s: ocp.ArrayRestoreArgs(sharding=s, dtype=x.dtype),
            engine.state, engine.state_shardings)
        restored = ckptr.restore(path / "state", item=abstract,
                                 restore_args=restore_args)
        engine.state = restored
        step_guess = int(restored.step)
    engine.global_steps = int(meta_pre.get("global_steps", step_guess))
    moq_meta = meta_pre.get("moq")
    if getattr(engine, "_moq", None) is not None:
        if moq_meta:
            engine._moq.bits = int(moq_meta["bits"])
            engine._moq.initial_eig = moq_meta.get("initial_eig")
            engine._moq.history = [tuple(h)
                                   for h in moq_meta.get("history", [])]
        else:
            # no schedule in the checkpoint (pre-MoQ save): RESET to the
            # fresh state — keeping an already-narrowed in-process schedule
            # would silently diverge from a fresh-process resume of the
            # same checkpoint
            moq = engine._moq
            cfg_wq = engine.config.compression.weight_quantization
            moq.bits = int(cfg_wq.start_bits or cfg_wq.bits)
            moq.initial_eig = None
            moq.history = []
            log_dist("load_checkpoint: MoQ enabled but the checkpoint "
                     "carries no schedule (pre-MoQ save?) — QAT restarts "
                     f"at start_bits={moq.bits}", ranks=[0])
    log_dist(f"loaded checkpoint {path} (step {engine.global_steps})", ranks=[0])
    return str(path)
