"""Checkpoint save/load.

TPU-native analog of the reference checkpoint stack (``engine.py:2653,2982``
+ pluggable ``runtime/checkpoint_engine/``): one *logical* checkpoint in a
sharded array store (orbax/tensorstore), written collectively by all hosts —
universal-by-construction. Where the reference writes per-(dp,tp,pp)-rank
shard files and needs an offline converter (``checkpoint/ds_to_universal.py``)
to reshape between topologies, here restore-onto-any-mesh is native: load
targets are specified as abstract (shape, sharding) and tensorstore reshards.

Layout per tag directory:
    <dir>/<tag>/state/...      sharded TrainState (master params, moments, step)
    <dir>/<tag>/meta.json      config + model metadata
    <dir>/latest               tag pointer (same contract as the reference)
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from ...utils.logging import log_dist


def _checkpointer():
    return ocp.PyTreeCheckpointer()


def save_checkpoint(engine, save_dir: str, tag: str | None = None) -> str:
    tag = tag or f"global_step{engine.global_steps}"
    base = Path(save_dir).absolute()
    path = base / tag
    ckptr = _checkpointer()
    if getattr(engine, "offload", False):
        # host-resident state (ZeRO-Offload/Infinity): numpy trees
        m, v = engine.host_opt.moment_trees()
        state = {"master_params": engine.host_opt.master_tree(),
                 "mu": m, "count": np.int32(engine.host_opt.count)}
        if v is not None:
            state["nu"] = v
        ckptr.save(path / "state", state, force=True)
    else:
        ckptr.save(path / "state", engine.state, force=True)
    if jax.process_index() == 0:
        meta = {
            "tag": tag,
            "global_steps": engine.global_steps,
            "config": engine.config.to_dict(),
            "param_count": engine.param_count,
            "mesh": dict(engine.mesh.shape),
        }
        (path / "meta.json").write_text(json.dumps(meta, indent=2))
        (base / "latest").write_text(tag)
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return str(path)


def load_checkpoint(engine, load_dir: str, tag: str | None = None) -> str:
    base = Path(load_dir).absolute()
    if tag is None:
        latest = base / "latest"
        if not latest.exists():
            raise FileNotFoundError(f"no 'latest' tag file in {base}")
        tag = latest.read_text().strip()
    path = base / tag
    ckptr = _checkpointer()
    if getattr(engine, "offload", False):
        restored = ckptr.restore(path / "state")
        engine.host_opt.load_state(restored["master_params"],
                                   restored.get("mu"), restored.get("nu"),
                                   count=int(restored["count"]))
        with engine.mesh:
            engine.compute_params = engine.host_opt.device_compute_params()
        engine.global_steps = int(restored["count"])
        step_guess = engine.global_steps
    else:
        # Abstract target carries this engine's shardings: restoring onto a
        # different mesh/topology reshards transparently (elastic resume).
        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            engine.state, engine.state_shardings)
        restored = ckptr.restore(path / "state", item=abstract)
        engine.state = restored
        step_guess = int(restored.step)
    meta_file = path / "meta.json"
    if meta_file.exists():
        meta = json.loads(meta_file.read_text())
        engine.global_steps = int(meta.get("global_steps", step_guess))
    else:
        engine.global_steps = step_guess
    log_dist(f"loaded checkpoint {path} (step {engine.global_steps})", ranks=[0])
    return str(path)
