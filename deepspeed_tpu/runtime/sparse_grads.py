"""Sparse embedding-gradient compression for host transfers.

Analog of the reference's ``SparseTensor`` + sparse allreduce for embedding
gradients (``runtime/sparse_tensor.py``, ``engine.py:2412-2480``): a batch
touches only a small subset of a large vocabulary, so the embedding gradient
is row-sparse. Under pure XLA data-parallel training the gradient reduction
is compiler-managed and dense; where row sparsity PAYS on TPU is the
offload path's device→host gradient transfer (``sparse_gradients: true`` in
the engine config): the grad step top-k-selects the touched embedding rows
on device (static bound: one row per batch token) and ships
``(indices, values)`` over the wire instead of the dense (V, d) table —
``HostOffloadOptimizer.step`` decompresses into the dense host buffer the
native optimizer consumes. The reference flag of the same name gates its
sparse embedding allreduce."""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np


class SparseGradRows(NamedTuple):
    """Device-side row-sparse gradient (a JAX pytree by NamedTuple):
    ``values[i]`` is the grad row for vocab id ``indices[i]``. Produced by
    the engine's grad step under ``sparse_gradients``; rows beyond the
    actually-touched count carry zero values (top-k bound is static)."""

    indices: Any               # (k,) int32 device array
    values: Any                # (k, d) device array


class SparseRows(NamedTuple):
    """Row-sparse matrix: ``values[i]`` is the gradient row for
    ``indices[i]``; shape is the dense (V, d)."""

    indices: np.ndarray        # (nnz,) int32 unique row ids
    values: np.ndarray         # (nnz, d)
    shape: tuple

    @property
    def density(self) -> float:
        return len(self.indices) / max(1, self.shape[0])


def compress_rows(dense: np.ndarray, threshold: float = 0.0) -> SparseRows:
    """Dense (V, d) grad → row-sparse form (rows with any |entry| >
    threshold kept)."""
    keep = np.where(np.abs(dense).max(axis=1) > threshold)[0]
    return SparseRows(indices=keep.astype(np.int32),
                      values=np.ascontiguousarray(dense[keep]),
                      shape=tuple(dense.shape))


def decompress_rows(sp: SparseRows) -> np.ndarray:
    out = np.zeros(sp.shape, sp.values.dtype)
    out[sp.indices] = sp.values
    return out


def add_into(dense: np.ndarray, sp: SparseRows) -> np.ndarray:
    """Accumulate a sparse grad into a dense buffer (the host-optimizer
    consumption path)."""
    np.add.at(dense, sp.indices, sp.values)
    return dense


def maybe_compress(dense: np.ndarray, max_density: float = 0.5):
    """Compress when it pays (reference keeps dense beyond ~half density):
    returns SparseRows or the dense array unchanged."""
    sp = compress_rows(dense)
    return sp if sp.density <= max_density else dense
