"""1-bit optimizers: OnebitAdam, OnebitLamb, ZeroOneAdam.

Analog of the reference's error-compensated compressed-communication
optimizers (``runtime/fp16/onebit/adam.py:14``, ``lamb.py:15``,
``zoadam.py:14``, 1,110 LoC) over its cupy sign-packing backends
(``runtime/comm/nccl.py:51``).  The algorithmic contract:

- **warmup** (step < freeze_step): exact Adam with a full-precision gradient
  all-reduce — the variance (nu) must stabilize before compression starts.
- **compressed**: nu is FROZEN; each rank folds its LOCAL gradient into the
  momentum (t_r = β1·mu + (1−β1)·g_r) and the cross-rank mean of t_r runs
  through the 1-bit error-feedback collective
  (:func:`deepspeed_tpu.comm.compressed.onebit_allreduce_mean`) — signs travel
  bit-packed (~16× fewer bytes than bf16). Because the collective is linear
  up to the compression error, mean_r(t_r) = β1·mu + (1−β1)·mean(g), i.e.
  the true momentum update plus error-feedback noise — exactly the
  reference's ``compressed_allreduce(exp_avg)``.
- **OnebitLamb** adds the per-leaf trust ratio (reference fused-LAMB
  semantics) on the final update.
- **ZeroOneAdam** never warms up; it refreshes the frozen variance from the
  momentum at steps ``var_update_interval * 2^j`` — the reference's doubling
  variance-update policy — so compression starts at step 0.

Metric note: in the compressed phase the global gradient is never
materialized, so the reported ``grad_norm`` is the TRUE gradient norm during
warmup and the synchronized MOMENTUM norm afterwards (the only global
quantity that exists).

TPU shape: the phase (warmup vs compressed) is a static jit argument — two
traces per run, no in-graph branching across different collectives. The
whole update runs under the engine's manual-``data`` shard_map, so only the
slow data hop carries compressed bytes; zero/model/seq sub-axes stay GSPMD.
Constraints (mirroring the reference): ZeRO stage 0 (replicated masters),
no offload, fp16 loss-scale skip unsupported (bf16 is the TPU default).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.compressed import chunk_elems, flatten_tree, onebit_allreduce_mean
from .optimizers import OptState

ONEBIT_TYPES = ("onebit_adam", "onebit_lamb", "zero_one_adam")


@dataclasses.dataclass(frozen=True)
class OnebitConfig:
    kind: str
    lr: float = 1e-3
    betas: tuple = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    freeze_step: int = 100            # warmup length (onebit_adam/lamb)
    var_update_interval: int = 16     # zero_one_adam nu refresh cadence
    max_coeff: float = 10.0           # lamb trust-ratio clip (reference)
    min_coeff: float = 0.01

    @classmethod
    def from_params(cls, kind: str, params: dict) -> "OnebitConfig":
        known = {f.name for f in dataclasses.fields(cls)} - {"kind"}
        clean = {k: (tuple(v) if k == "betas" else v)
                 for k, v in params.items() if k in known}
        unknown = set(params) - known
        if unknown - {"bias_correction"}:
            raise ValueError(f"unknown {kind} params: {sorted(unknown)}")
        return cls(kind=kind, **clean)


def in_warmup(cfg: OnebitConfig, step: int) -> bool:
    if cfg.kind == "zero_one_adam":
        return False                   # 0/1 Adam compresses from step 0
    return step < cfg.freeze_step


def onebit_train_step(engine, state, batch, scale, warmup: bool):
    """The 1-bit optimizer step: local grads → momentum sync (exact in
    warmup, 1-bit otherwise) → Adam/LAMB update with frozen variance.
    Returns (new_master, new_opt, new_comm_err, loss, gnorm)."""
    cfg: OnebitConfig = engine.onebit
    b1, b2 = cfg.betas
    D = int(engine.mesh.shape["data"])
    compute_params = engine._cast_compute(state.master_params)

    def body(cp, b, ce, mu_tree):
        grads, loss = engine._gas_scan(cp, b, scale)
        g_flat, unflatten = flatten_tree(grads)
        g_flat = g_flat / scale
        mu_flat, _ = flatten_tree(mu_tree)
        if warmup or D == 1:
            g_mean = lax.pmean(g_flat, "data") if D > 1 else g_flat
            m_new = b1 * mu_flat + (1.0 - b1) * g_mean
            new_ce = ce
        else:
            t = b1 * mu_flat + (1.0 - b1) * g_flat
            m_new, nw, ns = onebit_allreduce_mean(
                t, ce["worker"][0], ce["server"][0], "data")
            g_mean = jnp.zeros_like(g_flat)   # nu frozen: grads not needed
            new_ce = {"worker": nw[None], "server": ns[None]}
        loss = lax.pmean(loss, "data") if D > 1 else loss
        return unflatten(g_mean), unflatten(m_new), loss, new_ce

    fn = jax.shard_map(
        body, mesh=engine.mesh, axis_names=frozenset({"data"}),
        in_specs=(P(), P(None, "data"), P("data"), P()),
        out_specs=(P(), P(), P(), P("data")), check_vma=False)
    g_mean, m_new, loss, new_ce = fn(compute_params, batch, state.comm_err,
                                     state.opt_state.mu)

    count = state.opt_state.count + 1
    c = count.astype(jnp.float32)
    bc1 = 1.0 - b1 ** c
    bc2 = 1.0 - b2 ** c
    lr = engine.lr_schedule(state.step)

    if warmup:
        nu_new = jax.tree.map(lambda v, g: b2 * v + (1.0 - b2) * jnp.square(g),
                              state.opt_state.nu, g_mean)
    elif cfg.kind == "zero_one_adam":
        # Doubling-interval variance refresh (reference 0/1 Adam policy):
        # refresh at s = 0 (variance must initialize — nu starts at zero) and
        # at s = interval * 2^j, i.e. q = s/interval a power of two.
        k = jnp.int32(max(1, cfg.var_update_interval))
        q = state.step // k
        refresh = ((state.step % k) == 0) & ((q & (q - 1)) == 0)
        nu_new = jax.tree.map(
            lambda v, m: jnp.where(refresh, b2 * v + (1.0 - b2) * jnp.square(m), v),
            state.opt_state.nu, m_new)
    else:
        nu_new = state.opt_state.nu

    def upd(p, m, v):
        u = (m / bc1) / (jnp.sqrt(v / bc2) + cfg.eps)
        if cfg.weight_decay:
            u = u + cfg.weight_decay * p
        if cfg.kind == "onebit_lamb":
            wn = jnp.linalg.norm(p.reshape(-1))
            un = jnp.linalg.norm(u.reshape(-1))
            trust = jnp.where(un > 0, wn / jnp.maximum(un, 1e-12), 1.0)
            trust = jnp.clip(trust, cfg.min_coeff, cfg.max_coeff)
            u = u * trust
        return p - lr * u

    new_master = jax.tree.map(upd, state.master_params, m_new, nu_new)
    # warmup: true gradient norm; compressed: momentum norm (the gradient is
    # never globally materialized — see module docstring)
    norm_tree = g_mean if warmup else m_new
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(m))
                         for m in jax.tree.leaves(norm_tree)))
    new_opt = OptState(mu=m_new, nu=nu_new, count=count)
    return new_master, new_opt, new_ce, loss, gnorm, lr


def comm_err_shapes(param_count: int, data_world: int) -> dict:
    """Error-feedback residual shapes (leading dim = data axis)."""
    per = chunk_elems(param_count, data_world)
    return {"worker": (data_world, per * data_world),
            "server": (data_world, per)}
