"""LoRA adapters as a functional param-tree transform.

Reference analog: the hybrid engine's LoRA fuse/unfuse around RLHF
generate (``module_inject/containers/features/hybrid_engine.py:12``) and
DeepSpeed-Chat's ``only_optimize_lora`` actor
(``blogs/deepspeed-chat/README.md:41``). The torch version walks modules,
swaps Linear for LinearLayer_LoRA, and physically fuses W += B·A before
each generate; here the adapters are just an extra ``"lora"`` subtree in
the param pytree:

- **train**: the loss path merges ``W + (alpha/r)·A·B`` inside the compute
  cast (bf16 A·B is two small matmuls per layer stack — XLA fuses the add
  into the consumer). The base leaves are wrapped in ``stop_gradient`` and
  additionally pinned by the engine's frozen-param mask, so the optimizer
  updates adapters ONLY — weight decay cannot drift the frozen base.
- **generate**: the hybrid engine merges once up front and runs the plain
  decode loop over the merged tree — "fused" generate with no module
  surgery to unwind afterwards.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

# matmul leaves eligible for adapters (attention + FFN projections — the
# reference's LinearLayer_LoRA targets; cq/ck/cv/co are T5 cross-attention)
LORA_TARGETS = ("wq", "wk", "wv", "wo", "w_in", "w_out", "w_gate",
                "cq", "ck", "cv", "co")


def _layer_groups(params):
    """Yield (group_path, layers_dict) for every layer stack a model
    carries: decoder trunks have a top-level ``layers``; T5 has
    ``enc.layers`` and ``dec.layers``."""
    if isinstance(params.get("layers"), dict):
        yield ("layers",), params["layers"]
    for side in ("enc", "dec"):
        sub = params.get(side)
        if isinstance(sub, dict) and isinstance(sub.get("layers"), dict):
            yield (side, "layers"), sub["layers"]


class LoRAMixin:
    """Model wrapper: params carry a ``lora`` subtree of (A, B) pairs."""

    lora_rank: int = 8
    lora_alpha: float = 16.0
    lora_targets: tuple = LORA_TARGETS

    @property
    def _lora_scale(self) -> float:
        return self.lora_alpha / self.lora_rank

    def init(self, rng):
        base = super().init(rng)
        r = self.lora_rank
        lora = {}
        key = jax.random.fold_in(rng, 0x10F4)
        groups = list(_layer_groups(base))
        if not groups:
            raise ValueError(
                "lora: this model exposes no layer stack "
                "(params['layers'] / params['enc'|'dec']['layers'])")
        for gpath, layers in groups:
            bank = lora
            for k in gpath[:-1]:
                bank = bank.setdefault(k, {})
            bank = bank.setdefault(gpath[-1], {})
            for name in self.lora_targets:
                w = layers.get(name)
                if w is None or w.ndim < 2:
                    continue
                key, sub = jax.random.split(key)
                *lead, d_in, d_out = w.shape
                # standard LoRA init: A gaussian, B zero → identity at step 0
                bank[name] = {
                    "a": jax.random.normal(sub, (*lead, d_in, r), jnp.float32)
                    / math.sqrt(d_in),
                    "b": jnp.zeros((*lead, r, d_out), jnp.float32),
                }
        base["lora"] = lora
        return base

    def _abstract_params(self):
        return jax.eval_shape(self.init, jax.random.PRNGKey(0))

    def param_specs(self):
        specs = super().param_specs()
        # adapters are small: replicate (None spec) across the mesh
        specs["lora"] = jax.tree.map(lambda _: None,
                                     self._abstract_params()["lora"])
        return specs

    def frozen_param_mask(self):
        """Static bool pytree over init(): True = the engine must not
        update this leaf (every base leaf; adapters stay trainable)."""

        def mark(path, _):
            return not any(getattr(e, "key", None) == "lora" for e in path)

        return jax.tree_util.tree_map_with_path(mark, self._abstract_params())

    def merge_lora(self, params):
        """Base tree with adapters folded in: W + (alpha/r)·A·B. The base
        is stop_gradient'd — gradients exist only through A/B."""
        if "lora" not in params:
            return params
        merged = dict(params)
        lora = merged.pop("lora")

        def merge_bank(layers, bank):
            layers = dict(layers)
            for name, ab in bank.items():
                w = layers[name]
                delta = jnp.einsum("...dr,...rk->...dk",
                                   ab["a"].astype(w.dtype),
                                   ab["b"].astype(w.dtype))
                layers[name] = (jax.lax.stop_gradient(w)
                                + self._lora_scale * delta)
            return layers

        # walk the SAME groups init() created banks for (one source of
        # truth: a stack known to _layer_groups but skipped here would
        # train its adapters as a silent no-op)
        for gpath, layers in _layer_groups(merged):
            bank = lora
            for k in gpath:
                bank = bank.get(k, {})
            if not bank:
                continue
            if len(gpath) == 1:
                merged["layers"] = merge_bank(layers, bank)
            else:
                sub = dict(merged[gpath[0]])
                sub["layers"] = merge_bank(layers, bank)
                merged[gpath[0]] = sub
        return merged

    def loss(self, params, batch, **kw):
        return super().loss(self.merge_lora(params), batch, **kw)

    def apply(self, params, input_ids, *args, **kw):
        # *args: T5's apply takes decoder_input_ids positionally
        return super().apply(self.merge_lora(params), input_ids, *args, **kw)


def convert_to_lora(model, *, rank: int = 8, alpha: float = 16.0,
                    targets=LORA_TARGETS):
    """Wrap a built model with LoRA (same class-mixin mechanism as PLD)."""
    cls = type(model)
    new_cls = type(f"LoRA{cls.__name__}", (LoRAMixin, cls), {})
    new = object.__new__(new_cls)
    new.__dict__.update(model.__dict__)
    new.lora_rank = int(rank)
    new.lora_alpha = float(alpha)
    new.lora_targets = tuple(targets)
    return new
