"""Checkpoint export: native params → HF-format state dict / safetensors.

Analog of the reference's offline consolidation tools —
``utils/zero_to_fp32.py`` (reconstruct full fp32 weights from ZeRO shards,
587 LoC) and ``engine._zero3_consolidated_16bit_state_dict``
(``engine.py:3395``): produce a checkpoint other stacks can load.  Because
the orbax store is one logical sharded checkpoint, "consolidation" is just a
replicated restore; the interesting half is the NAME mAPPING — the exact
inverse of :mod:`deepspeed_tpu.models.importer` (unstack the (L, ...) scan
layout, re-fuse GPT-2's c_attn, undo the RoPE basis permutation, transpose
back to torch (out, in)) so ``import → export`` round-trips bit-exactly.
"""

from __future__ import annotations

import json
import os
from typing import Dict

import numpy as np

from ..utils.logging import log_dist
from .importer import _rope_interleave_perm
from .transformer import TransformerConfig

__all__ = ["export_state_dict", "export_hf_checkpoint"]


def _inv_perm(perm: np.ndarray) -> np.ndarray:
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    return inv


def _np(p) -> np.ndarray:
    return np.asarray(p)


def _gpt2_export(params: dict, cfg: TransformerConfig) -> Dict[str, np.ndarray]:
    L = cfg.n_layer
    lay = params["layers"]
    sd: Dict[str, np.ndarray] = {
        "transformer.wte.weight": _np(params["tok_embed"]),
        "transformer.wpe.weight": _np(params["pos_embed"]),
        "transformer.ln_f.weight": _np(params["lnf_scale"]),
        "transformer.ln_f.bias": _np(params["lnf_bias"]),
    }
    for i in range(L):
        h = f"transformer.h.{i}."
        sd[h + "attn.c_attn.weight"] = np.concatenate(
            [_np(lay["wq"][i]), _np(lay["wk"][i]), _np(lay["wv"][i])], axis=1)
        sd[h + "attn.c_attn.bias"] = np.concatenate(
            [_np(lay["bq"][i]), _np(lay["bk"][i]), _np(lay["bv"][i])])
        sd[h + "attn.c_proj.weight"] = _np(lay["wo"][i])
        sd[h + "attn.c_proj.bias"] = _np(lay["bo"][i])
        sd[h + "ln_1.weight"] = _np(lay["ln1_scale"][i])
        sd[h + "ln_1.bias"] = _np(lay["ln1_bias"][i])
        sd[h + "ln_2.weight"] = _np(lay["ln2_scale"][i])
        sd[h + "ln_2.bias"] = _np(lay["ln2_bias"][i])
        sd[h + "mlp.c_fc.weight"] = _np(lay["w_in"][i])
        sd[h + "mlp.c_fc.bias"] = _np(lay["b_in"][i])
        sd[h + "mlp.c_proj.weight"] = _np(lay["w_out"][i])
        sd[h + "mlp.c_proj.bias"] = _np(lay["b_out"][i])
    return sd


def _llama_export(params: dict, cfg: TransformerConfig) -> Dict[str, np.ndarray]:
    hd = cfg.head_dim
    q_inv = _inv_perm(_rope_interleave_perm(cfg.n_head, hd))
    kv_inv = _inv_perm(_rope_interleave_perm(cfg.kv_heads, hd))
    lay = params["layers"]
    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": _np(params["tok_embed"]),
        "model.norm.weight": _np(params["lnf_scale"]),
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = _np(params["lm_head"]).T
    for i in range(cfg.n_layer):
        h = f"model.layers.{i}."
        sd[h + "input_layernorm.weight"] = _np(lay["ln1_scale"][i])
        sd[h + "post_attention_layernorm.weight"] = _np(lay["ln2_scale"][i])
        sd[h + "self_attn.q_proj.weight"] = _np(lay["wq"][i])[:, q_inv].T
        sd[h + "self_attn.k_proj.weight"] = _np(lay["wk"][i])[:, kv_inv].T
        sd[h + "self_attn.v_proj.weight"] = _np(lay["wv"][i]).T
        sd[h + "self_attn.o_proj.weight"] = _np(lay["wo"][i]).T
        sd[h + "mlp.gate_proj.weight"] = _np(lay["w_gate"][i]).T
        sd[h + "mlp.up_proj.weight"] = _np(lay["w_in"][i]).T
        sd[h + "mlp.down_proj.weight"] = _np(lay["w_out"][i]).T
    return sd


def _opt_export(params: dict, cfg: TransformerConfig) -> Dict[str, np.ndarray]:
    lay = params["layers"]
    pos = _np(params["pos_embed"])
    sd: Dict[str, np.ndarray] = {
        "model.decoder.embed_tokens.weight": _np(params["tok_embed"]),
        # HF quirk: positions are offset by 2; rows 0-1 are never read
        "model.decoder.embed_positions.weight": np.concatenate(
            [np.zeros((2, pos.shape[1]), pos.dtype), pos]),
        "model.decoder.final_layer_norm.weight": _np(params["lnf_scale"]),
        "model.decoder.final_layer_norm.bias": _np(params["lnf_bias"]),
    }
    for i in range(cfg.n_layer):
        h = f"model.decoder.layers.{i}."
        sd[h + "self_attn_layer_norm.weight"] = _np(lay["ln1_scale"][i])
        sd[h + "self_attn_layer_norm.bias"] = _np(lay["ln1_bias"][i])
        for ours, theirs in (("wq", "q_proj"), ("wk", "k_proj"),
                             ("wv", "v_proj"), ("wo", "out_proj")):
            sd[h + f"self_attn.{theirs}.weight"] = _np(lay[ours][i]).T
        for ours, theirs in (("bq", "q_proj"), ("bk", "k_proj"),
                             ("bv", "v_proj"), ("bo", "out_proj")):
            sd[h + f"self_attn.{theirs}.bias"] = _np(lay[ours][i])
        sd[h + "final_layer_norm.weight"] = _np(lay["ln2_scale"][i])
        sd[h + "final_layer_norm.bias"] = _np(lay["ln2_bias"][i])
        sd[h + "fc1.weight"] = _np(lay["w_in"][i]).T
        sd[h + "fc1.bias"] = _np(lay["b_in"][i])
        sd[h + "fc2.weight"] = _np(lay["w_out"][i]).T
        sd[h + "fc2.bias"] = _np(lay["b_out"][i])
    return sd


def _detect_family(cfg: TransformerConfig) -> str:
    if not cfg.causal or cfg.pos_embedding == "alibi":
        raise ValueError(
            "no HF export mapping for encoder/ALiBi trunks (BERT/Bloom); "
            "pass an explicit supported family or export the raw pytree")
    if cfg.norm == "rmsnorm" and cfg.pos_embedding == "rope":
        return "llama"
    if cfg.activation == "relu" and cfg.pos_embedding == "learned":
        return "opt"
    if (cfg.activation == "gelu" and cfg.pos_embedding == "learned"
            and cfg.norm == "layernorm"):
        # structurally ambiguous with gelu-activation OPT variants
        # (Galactica); those must pass family="opt" explicitly
        return "gpt2"
    raise ValueError(
        f"cannot auto-detect the HF export family (pos={cfg.pos_embedding}, "
        f"norm={cfg.norm}, act={cfg.activation}); pass family= explicitly")


_EXPORTERS = {"gpt2": _gpt2_export, "llama": _llama_export,
              "mistral": _llama_export, "opt": _opt_export}


def export_state_dict(params: dict, cfg: TransformerConfig,
                      family: str | None = None) -> Dict[str, np.ndarray]:
    """Native param pytree → HF-format numpy state dict (fp32)."""
    if cfg.num_experts > 1:
        raise ValueError(
            "MoE trunks have no HF export mapping yet (stacked expert banks "
            "+ router don't fit the dense llama names; a Mixtral exporter "
            "would need per-expert unstacking)")
    family = family or _detect_family(cfg)
    if family not in _EXPORTERS:
        raise ValueError(f"unsupported export family {family!r}")
    return _EXPORTERS[family](params, cfg)


def export_hf_checkpoint(params: dict, cfg: TransformerConfig, out_dir: str,
                         family: str | None = None) -> str:
    """Write an HF-style checkpoint dir (config.json + model.safetensors)
    loadable by transformers or re-importable by
    :func:`~deepspeed_tpu.models.load_hf_checkpoint`."""
    from safetensors.numpy import save_file

    family = family or _detect_family(cfg)
    sd = export_state_dict(params, cfg, family)
    os.makedirs(out_dir, exist_ok=True)
    hf_cfg = _hf_config_for(cfg, family)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)
    save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
              os.path.join(out_dir, "model.safetensors"))
    log_dist(f"exported {family} checkpoint → {out_dir} "
             f"({len(sd)} tensors)", ranks=[0])
    return out_dir


def _hf_config_for(cfg: TransformerConfig, family: str) -> dict:
    if family == "gpt2":
        return {"model_type": "gpt2", "vocab_size": cfg.vocab_size,
                "n_layer": cfg.n_layer, "n_head": cfg.n_head,
                "n_embd": cfg.d_model, "n_inner": cfg.ffn_dim,
                "n_positions": cfg.max_seq,
                "layer_norm_epsilon": cfg.norm_eps}
    if family in ("llama", "mistral"):
        return {"model_type": family, "vocab_size": cfg.vocab_size,
                "num_hidden_layers": cfg.n_layer,
                "num_attention_heads": cfg.n_head,
                "num_key_value_heads": cfg.kv_heads,
                "hidden_size": cfg.d_model,
                "intermediate_size": cfg.ffn_dim,
                "max_position_embeddings": cfg.max_seq,
                "rope_theta": cfg.rope_theta, "rms_norm_eps": cfg.norm_eps,
                "tie_word_embeddings": cfg.tie_embeddings,
                # explicit null: MistralConfig would default 4096 and HF
                # would silently window attention the trunk never applied
                "sliding_window": None}
    if family == "opt":
        return {"model_type": "opt", "vocab_size": cfg.vocab_size,
                "num_hidden_layers": cfg.n_layer,
                "num_attention_heads": cfg.n_head,
                "hidden_size": cfg.d_model, "ffn_dim": cfg.ffn_dim,
                "max_position_embeddings": cfg.max_seq,
                "activation_function": cfg.activation,
                "do_layer_norm_before": True}
    raise ValueError(family)
