"""Model-family presets over the unified TransformerLM.

Covers the model families exercised by the reference baselines (BASELINE.md):
GPT-2 (125M/1.5B), Llama-2 (7B/13B/70B), BERT-class encoder sizes are served
by the same trunk with ``causal=False`` planned, Mixtral via ``num_experts``.
"""

from __future__ import annotations

from .transformer import TransformerConfig, TransformerLM


def gpt2(size: str = "125m", **overrides) -> TransformerConfig:
    table = {
        "125m": dict(n_layer=12, n_head=12, d_model=768),
        "350m": dict(n_layer=24, n_head=16, d_model=1024),
        "774m": dict(n_layer=36, n_head=20, d_model=1280),
        "1.5b": dict(n_layer=48, n_head=25, d_model=1600),
    }
    base = dict(vocab_size=50257, max_seq=1024, pos_embedding="learned",
                norm="layernorm", activation="gelu", use_bias=True,
                tie_embeddings=True)
    base.update(table[size])
    base.update(overrides)
    return TransformerConfig(**base)


def llama2(size: str = "7b", **overrides) -> TransformerConfig:
    table = {
        "tiny": dict(n_layer=4, n_head=8, n_kv_head=4, d_model=256, d_ff=688),
        "7b": dict(n_layer=32, n_head=32, d_model=4096, d_ff=11008),
        "13b": dict(n_layer=40, n_head=40, d_model=5120, d_ff=13824),
        "70b": dict(n_layer=80, n_head=64, n_kv_head=8, d_model=8192, d_ff=28672),
    }
    base = dict(vocab_size=32000, max_seq=4096, pos_embedding="rope",
                norm="rmsnorm", activation="silu_glu", use_bias=False,
                tie_embeddings=False)
    base.update(table[size])
    base.update(overrides)
    return TransformerConfig(**base)


def mixtral(size: str = "8x7b", **overrides) -> TransformerConfig:
    table = {
        "tiny": dict(n_layer=4, n_head=8, n_kv_head=4, d_model=256, d_ff=512,
                     num_experts=4, moe_top_k=2),
        "8x7b": dict(n_layer=32, n_head=32, n_kv_head=8, d_model=4096, d_ff=14336,
                     num_experts=8, moe_top_k=2),
    }
    base = dict(vocab_size=32000, max_seq=4096, pos_embedding="rope",
                norm="rmsnorm", activation="silu_glu", use_bias=False,
                tie_embeddings=False)
    base.update(table[size])
    base.update(overrides)
    return TransformerConfig(**base)


def bert(size: str = "base", **overrides) -> TransformerConfig:
    """Encoder (bidirectional) trunk + MLM objective — the BERT family the
    reference's flagship pretraining baseline uses
    (``docs/_tutorials/bert-pretraining.md``)."""
    table = {
        "tiny": dict(n_layer=2, n_head=4, d_model=128, d_ff=512, max_seq=128),
        "base": dict(n_layer=12, n_head=12, d_model=768, max_seq=512),
        "large": dict(n_layer=24, n_head=16, d_model=1024, max_seq=512),
    }
    base = dict(vocab_size=30522, pos_embedding="learned", norm="layernorm",
                activation="gelu", use_bias=True, tie_embeddings=True,
                causal=False, objective="mlm")
    base.update(table[size])
    base.update(overrides)
    return TransformerConfig(**base)


def opt(size: str = "125m", **overrides) -> TransformerConfig:
    """OPT family (reference inference container ``containers/opt.py``):
    decoder with learned positions and ReLU FFN."""
    table = {
        "tiny": dict(n_layer=2, n_head=4, d_model=64, d_ff=256, max_seq=64),
        "125m": dict(n_layer=12, n_head=12, d_model=768),
        "1.3b": dict(n_layer=24, n_head=32, d_model=2048),
        "6.7b": dict(n_layer=32, n_head=32, d_model=4096),
        "13b": dict(n_layer=40, n_head=40, d_model=5120),
    }
    base = dict(vocab_size=50272, max_seq=2048, pos_embedding="learned",
                norm="layernorm", activation="relu", use_bias=True,
                tie_embeddings=True)
    base.update(table[size])
    base.update(overrides)
    return TransformerConfig(**base)


def bloom(size: str = "560m", **overrides) -> TransformerConfig:
    """Bloom family (reference container ``containers/bloom.py``): ALiBi
    position bias, no positional table. HF checkpoints import via the
    ``bloom`` family (fused per-head qkv split + embedding layernorm)."""
    table = {
        "tiny": dict(n_layer=2, n_head=4, d_model=64, d_ff=256, max_seq=64),
        "560m": dict(n_layer=24, n_head=16, d_model=1024),
        "7b": dict(n_layer=30, n_head=32, d_model=4096),
        "176b": dict(n_layer=70, n_head=112, d_model=14336),
    }
    base = dict(vocab_size=250880, max_seq=2048, pos_embedding="alibi",
                norm="layernorm", activation="gelu", use_bias=True,
                tie_embeddings=True)
    base.update(table[size])
    base.update(overrides)
    return TransformerConfig(**base)


def tiny_test(**overrides) -> TransformerConfig:
    """Unit-test sized config (analog of the reference tests' SimpleModel)."""
    base = dict(vocab_size=256, n_layer=2, n_head=4, d_model=64, d_ff=128,
                max_seq=64, tie_embeddings=True)
    base.update(overrides)
    return TransformerConfig(**base)


def build_model(cfg, attention_fn=None):
    from .t5 import T5Config, T5Model

    if isinstance(cfg, T5Config):
        assert attention_fn is None, "T5 uses its own unscaled attention"
        return T5Model(cfg)
    if cfg.num_experts > 1:
        from .moe import MoETransformerLM

        return MoETransformerLM(cfg, attention_fn=attention_fn)
    return TransformerLM(cfg, attention_fn=attention_fn)
